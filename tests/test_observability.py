"""Observability tests (ISSUE 8): registry correctness under concurrent
writers, Prometheus exposition golden format, the per-request span
timeline of a seeded scheduler run, Chrome-trace schema sanity, and the
/metrics + /healthz + /statusz endpoint round-trips (including a live
scrape during a serving run)."""

import json
import re
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle_tpu import fluid
from paddle_tpu.observability import (MetricsRegistry, ObservabilityServer,
                                      Sample, Tracer, registry, tracer)
from paddle_tpu.serving import ContinuousBatchingScheduler, PageAllocator


class FakeModel:
    """Minimal slot model (scheduler protocol): every lane emits token 5
    until max_new_tokens retires it — deterministic, no device work."""

    start_id, end_id = 0, 1

    def __init__(self, n=0):
        self.n = n

    def open_slots(self, n):
        self.n = n

    def admit_slot(self, slot, prompt):
        return len(prompt)

    def clear_slot(self, slot):
        pass

    def step_slots(self, tokens, pos, src_len):
        return np.full(self.n, 5, np.int64)

    def shard_plan(self):
        # mesh shape the scrape exposes per-shard (ISSUE 17): the
        # collector emits one shard_pool_bytes sample per model shard
        return {"mesh_axes": {"batch": 1, "model": 2},
                "shard_axis": "model", "n_model_shards": 2,
                "pool_bytes_per_shard": 4096.0}


# -- registry ----------------------------------------------------------------

def test_counter_concurrent_writers_exact():
    """N threads x K increments lose nothing (the whole point of the
    per-child lock: scheduler thread, watchdog, submitters all write)."""
    reg = MetricsRegistry()
    c = reg.counter("t_total", "t", labels=("who",))
    h = reg.histogram("t_lat", "t")
    n_threads, k = 8, 500

    def work(i):
        child = c.labels(who=f"w{i % 2}")
        for _ in range(k):
            child.inc()
            h.observe(0.01)

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(c.labels(who=f"w{i}").value for i in range(2))
    assert total == n_threads * k
    _, _, count = h.labels().snapshot()
    assert count == n_threads * k


def test_instrument_type_and_label_conflicts_raise():
    reg = MetricsRegistry()
    reg.counter("x_total", "x", labels=("a",))
    with pytest.raises(ValueError):
        reg.gauge("x_total")                    # kind conflict
    with pytest.raises(ValueError):
        reg.counter("x_total", labels=("b",))   # label-set conflict
    with pytest.raises(ValueError):
        reg.counter("bad name")                 # invalid name
    with pytest.raises(ValueError):
        reg.counter("y_total").inc(-1)          # counters only go up


def test_collector_weak_owner_and_accumulation():
    """Two collectors agreeing on (name, labels) SUM; a dead owner's
    collector drops out at the next scrape."""
    reg = MetricsRegistry()

    class Owner:
        def __init__(self, v):
            self.v = v

        def collect(self):
            yield Sample("pool_pages", "gauge", (("state", "free"),),
                         float(self.v), "h")

    a, b = Owner(3), Owner(4)
    reg.register_collector(a.collect)
    reg.register_collector(b.collect)
    assert "pool_pages{state=\"free\"} 7" in reg.render_prometheus()
    del b
    assert "pool_pages{state=\"free\"} 3" in reg.render_prometheus()


_LABEL = r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'  # escaped \" \\ \n ok
_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"                    # metric name
    rf"(\{{{_LABEL}(,{_LABEL})*\}})?"               # label set
    r" (-?[0-9.e+-]+|\+Inf|-Inf|NaN)$")             # value


def _assert_prometheus_valid(text):
    """Golden-format check: every line is a comment or a valid sample;
    every sample's family has HELP+TYPE; histograms are cumulative with
    a +Inf bucket and _sum/_count."""
    typed, helped = {}, set()
    samples = []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            helped.add(line.split()[2])
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            assert kind in ("counter", "gauge", "histogram"), line
            typed[name] = kind
            continue
        assert _LINE.match(line), f"bad exposition line: {line!r}"
        samples.append(line)
    hist = {n for n, k in typed.items() if k == "histogram"}
    for line in samples:
        name = re.split(r"[{ ]", line, 1)[0]
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in hist:
                base = name[:-len(suffix)]
        assert base in typed and base in helped, f"untyped series {name}"
    return typed


def test_prometheus_exposition_golden():
    reg = MetricsRegistry()
    c = reg.counter("req_total", "requests", labels=("event",))
    c.labels(event="ok").inc(3)
    c.labels(event='we"ird\nname').inc()         # label escaping
    g = reg.gauge("depth", "queue depth")
    g.set(2.5)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    text = reg.render_prometheus()
    typed = _assert_prometheus_valid(text)
    assert typed == {"req_total": "counter", "depth": "gauge",
                     "lat_seconds": "histogram"}
    assert 'req_total{event="ok"} 3' in text
    assert r'we\"ird\nname' in text
    # histogram: cumulative buckets, +Inf == count, sum is the total
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="1"} 2' in text
    assert 'lat_seconds_bucket{le="+Inf"} 3' in text
    assert "lat_seconds_count 3" in text
    assert "lat_seconds_sum 5.55" in text


def test_gauge_function_and_snapshot_json():
    reg = MetricsRegistry()
    reg.gauge("lazy", "sampled at scrape").set_function(lambda: 41 + 1)
    reg.histogram("h_seconds", "h").observe(0.2)
    snap = reg.snapshot()
    json.dumps(snap)                        # JSON-able, incl. bucket keys
    by_name = {m["name"]: m for m in snap["metrics"]}
    assert by_name["lazy"]["samples"][0]["value"] == 42
    assert by_name["h_seconds"]["samples"][0]["count"] == 1
    assert "+Inf" in by_name["h_seconds"]["samples"][0]["buckets"]


def test_histogram_percentile_interpolation():
    reg = MetricsRegistry()
    h = reg.histogram("p_seconds", "p", buckets=(0.1, 1.0, 10.0))
    for _ in range(90):
        h.observe(0.05)
    for _ in range(10):
        h.observe(5.0)
    assert h.percentile(50) <= 0.1
    assert 1.0 <= h.percentile(99) <= 10.0
    assert reg.histogram("empty_seconds", "e").percentile(50) is None


# -- tracer ------------------------------------------------------------------

def test_tracer_ring_bound_and_chrome_schema():
    tr = Tracer(capacity=16)
    for i in range(20):
        with tr.span("work", cat="test", i=i):
            pass
    evs = tr.events()
    assert len(evs) == 16 and tr.dropped == 4
    assert evs[0]["args"]["i"] == 4              # oldest dropped first
    ids = [e["id"] for e in evs]
    assert ids == sorted(ids)                    # seeded, monotonic ids
    trace = tr.chrome_trace()
    assert set(trace) == {"traceEvents", "displayTimeUnit"}
    for e in trace["traceEvents"]:
        assert {"name", "cat", "ph", "ts", "pid", "tid"} <= set(e)
        assert e["ph"] in ("X", "i")
        if e["ph"] == "X":
            assert e["dur"] >= 0


def test_tracer_disable_is_noop_and_export(tmp_path):
    tr = Tracer()
    tr.disable()
    with tr.span("skipped"):
        pass
    tr.instant("skipped2")
    assert tr.events() == []
    tr.enable()
    tr.instant("kept")
    path = tmp_path / "trace.json"
    assert tr.export(str(path)) == 1
    loaded = json.loads(path.read_text())
    assert loaded["traceEvents"][0]["name"] == "kept"


def test_profiler_record_event_threadsafe_and_traced():
    """Satellite: concurrent record_event loses no events, and the same
    events land in the tracer (table and trace agree on counts)."""
    from paddle_tpu.fluid import profiler

    tr = tracer()
    tr.clear()
    profiler.reset_profiler()
    n_threads, k = 6, 200
    with profiler.profiler(print_table=False):
        def work():
            for _ in range(k):
                with profiler.record_event("conc"):
                    pass

        threads = [threading.Thread(target=work)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rows = {r["name"]: r for r in profiler.get_profile_table()}
    assert rows["conc"]["calls"] == n_threads * k
    assert len(tr.events("conc")) == n_threads * k


# -- seeded scheduler timeline ------------------------------------------------

def test_scheduler_span_timeline_reconstructs_lifecycle():
    """The acceptance timeline: a seeded run's trace contains, per
    request, submitted <= admitted <= token* <= retired with token
    instants exactly equal to the emitted tokens, and the whole-request
    X span matching the Request's own timestamps."""
    tr = tracer()
    tr.clear()
    rng = np.random.RandomState(0)
    sched = ContinuousBatchingScheduler(FakeModel(), n_slots=2,
                                        max_new_tokens=6)
    reqs = [sched.submit(rng.randint(2, 9, rng.randint(1, 5)),
                         max_new_tokens=int(rng.randint(2, 6)))
            for _ in range(5)]
    sched.run_until_idle()
    assert all(r.done and r.error is None for r in reqs)

    def by_rid(name):
        out = {}
        for e in tr.events(name):
            out.setdefault(e["args"]["rid"], []).append(e)
        return out

    subs, adms, toks, rets = (by_rid(n) for n in (
        "request/submitted", "request/admitted", "request/token",
        "request/retired"))
    spans = by_rid("request")
    for r in reqs:
        assert len(subs[r.rid]) == len(adms[r.rid]) == 1
        assert len(rets[r.rid]) == 1
        # token instants == emitted tokens, indices 1..n in order
        assert [e["args"]["index"] for e in toks[r.rid]] == \
            list(range(1, len(r.tokens) + 1))
        # ordering along the ring's timestamps
        assert subs[r.rid][0]["ts"] <= adms[r.rid][0]["ts"]
        assert adms[r.rid][0]["ts"] <= toks[r.rid][0]["ts"]
        assert toks[r.rid][-1]["ts"] <= rets[r.rid][0]["ts"] + 1e-3
        assert rets[r.rid][0]["args"]["tokens"] == len(r.tokens)
        # the whole-request span is stamped from the Request's marks
        (sp,) = spans[r.rid]
        assert sp["ph"] == "X"
        assert sp["ts"] == pytest.approx(r.submitted * 1e6)
        assert sp["dur"] == pytest.approx(
            (r.finished - r.submitted) * 1e6)
        # and the Request's own clock ordering holds
        assert r.submitted <= r.admitted <= r.first_token <= r.finished
    # one scheduler/step span per lockstep step
    assert len(tr.events("scheduler/step")) == sched.stats()["steps"]


def test_scheduler_stats_percentiles_satellite():
    sched = ContinuousBatchingScheduler(FakeModel(), n_slots=2,
                                        max_new_tokens=4)
    for _ in range(4):
        sched.submit([2, 3])
    sched.run_until_idle()
    st = sched.stats()
    # existing keys untouched (PR 5/6 contract)...
    for k in ("steps", "finished", "p50_latency_s", "p95_latency_s",
              "decoded_tok_per_s"):
        assert k in st
    # ...new percentile keys ride along
    assert st["p99_latency_s"] >= st["p95_latency_s"] >= 0
    assert 0 <= st["ttft_p50_s"] <= st["ttft_p95_s"]
    assert st["ttft_p95_s"] <= st["p95_latency_s"] + 1e-9
    assert st["tokens_per_request"] == {"p50": 4.0, "p95": 4.0, "max": 4}


def test_paged_prefill_chunk_spans():
    """The prefill leg of the timeline: a chunked-prefill admission
    emits one lane/prefill_chunk instant per dispatched chunk, covering
    the prompt exactly."""
    from paddle_tpu.serving import PagedTransformerGenerator

    tr = tracer()
    gen = PagedTransformerGenerator(
        24, 24, n_layer=2, n_head=2, d_key=4, d_value=4, d_model=16,
        d_inner_hid=32, max_length=64, src_len=8, max_out_len=8,
        page_size=4, chunk_size=4, num_pages=32, param_prefix="tfobs",
        place=fluid.CPUPlace())
    gen.init_params(seed=3)
    gen.open_slots(1)
    s_true = 7                                   # 2 chunks: 4 + 3
    gen.admit_slot(0, np.arange(2, 2 + s_true), max_new=4)
    tr.clear()
    steps = 0
    while gen._lanes[0].phase == "prefill":
        gen.lane_step()
        steps += 1
    chunks = [e["args"] for e in tr.events("lane/prefill_chunk")]
    assert len(chunks) == 2 == steps
    assert [c["tokens"] for c in chunks] == [4, 3]
    assert chunks[-1]["done"] == s_true == chunks[-1]["total"]
    gen.clear_slot(0)


# -- endpoints ----------------------------------------------------------------

def _get(addr, route):
    with urllib.request.urlopen(f"http://{addr}{route}", timeout=10) as r:
        return r.read()


def test_endpoints_roundtrip_live_scrape_during_run():
    """The acceptance scrape: /metrics during a serving run exposes
    labeled queue-depth, slot/page-utilization, TTFT, and guardrail
    counters in valid Prometheus text; /healthz and /statusz answer."""
    exe = fluid.Executor(fluid.CPUPlace())          # guardrail collector
    pool = PageAllocator(num_pages=16, page_size=4)  # page collector
    pool.alloc(3)
    sched = ContinuousBatchingScheduler(FakeModel(), n_slots=2,
                                        max_new_tokens=64)
    srv = ObservabilityServer()
    srv.attach("scheduler", sched).attach("executor", exe)
    srv.attach("callable", lambda: {"custom": 1})
    addr = srv.start()
    try:
        sched.serve()
        try:
            reqs = [sched.submit([2, 3, 4]) for _ in range(8)]
            # live mid-run scrape (requests decode 64 tokens each, so
            # the run comfortably outlasts the scrape)
            text = _get(addr, "/metrics").decode()
            for r in reqs:
                assert r.wait(timeout=60)
        finally:
            sched.shutdown()
        typed = _assert_prometheus_valid(text)
        assert typed["paddle_serving_queue_depth"] == "gauge"
        assert typed["paddle_serving_slot_utilization"] == "gauge"
        assert typed["paddle_kv_page_utilization"] == "gauge"
        assert typed["paddle_serving_ttft_seconds"] == "histogram"
        assert typed["paddle_guardrail_events_total"] == "counter"
        assert 'paddle_kv_pages{state="in_use"}' in text
        assert 'paddle_serving_requests_total{event="submitted"}' in text
        # per-shard pool residency (ISSUE 17): one labeled sample per
        # mesh model-axis shard of every live model
        assert typed["paddle_serving_shard_pool_bytes"] == "gauge"
        for shard in ("0", "1"):
            assert re.search(
                r'^paddle_serving_shard_pool_bytes\{model="default",'
                rf'shard="{shard}"\}} 4096', text, re.M), text

        health = json.loads(_get(addr, "/healthz"))
        assert health["ok"] is True and health["uptime_s"] >= 0

        status = json.loads(_get(addr, "/statusz"))
        assert set(status["sources"]) == {"callable", "executor",
                                          "scheduler"}
        assert status["callable"] == {"custom": 1}
        # a single-stats-method source attaches flat (scheduler.stats);
        # multi-method sources (the executor) nest under the method name
        assert status["scheduler"]["finished"] == 8
        assert "executable" in status["executor"]["cache_stats"]
        assert "skips" in status["executor"]["health_stats"]

        trace = json.loads(_get(addr, "/trace"))
        assert any(e["name"] == "request/retired"
                   for e in trace["traceEvents"])

        # unknown route -> structured 404
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(addr, "/nope")
        assert err.value.code == 404
    finally:
        srv.stop()


def test_statusz_broken_source_is_isolated():
    srv = ObservabilityServer()
    srv.attach("bad", lambda: 1 / 0)
    srv.attach("good", lambda: {"v": 2})
    addr = srv.start()
    try:
        status = json.loads(_get(addr, "/statusz"))
        assert status["good"] == {"v": 2}
        assert "ZeroDivisionError" in status["bad"]["error"]
    finally:
        srv.stop()


def test_attach_rejects_unusable_source():
    srv = ObservabilityServer()
    try:
        with pytest.raises(TypeError):
            srv.attach("nope", object())
    finally:
        # stop() without start() must release the socket, not deadlock
        # on shutdown()'s serve_forever handshake
        srv.stop()


def test_slot_utilization_aggregates_not_sums():
    """Two live schedulers at full occupancy must report utilization
    <= 1.0 (aggregate ratio over summed counts, the paging.py rule) —
    a per-instance ratio collector would sum to 2.0."""
    scheds = [ContinuousBatchingScheduler(FakeModel(), n_slots=1,
                                          max_new_tokens=4)
              for _ in range(2)]
    for s in scheds:
        s.submit([2, 3])
        s._admit_pending()              # occupy the lane, don't decode
    text = registry().render_prometheus()
    m = re.search(r"^paddle_serving_slot_utilization (\S+)$", text,
                  re.M)
    assert m and 0.0 < float(m.group(1)) <= 1.0, m
    for s in scheds:
        s.run_until_idle()


def test_server_start_after_stop_raises():
    srv = ObservabilityServer()
    srv.start()
    srv.stop()
    with pytest.raises(RuntimeError, match="after stop"):
        srv.start()


def test_histogram_bucket_conflict_raises():
    reg = MetricsRegistry()
    reg.histogram("hb_seconds", "h", buckets=(1, 2))
    reg.histogram("hb_seconds", "h", buckets=(1, 2))      # same: fine
    with pytest.raises(ValueError, match="buckets"):
        reg.histogram("hb_seconds", "h", buckets=(5, 6))


def test_nan_gauge_renders_instead_of_breaking_scrape():
    """A broken set_function gauge reports NaN and the scrape survives
    — one bad lazy gauge must not 500 every series."""
    reg = MetricsRegistry()
    reg.gauge("broken", "raises at scrape").set_function(
        lambda: 1 / 0)
    reg.gauge("fine", "ok").set(3)
    text = reg.render_prometheus()
    _assert_prometheus_valid(text)
    assert "broken NaN" in text
    assert "fine 3" in text


def test_labels_mismatch_raises_valueerror_not_keyerror():
    reg = MetricsRegistry()
    c = reg.counter("lbl_total", "l", labels=("event",))
    with pytest.raises(ValueError, match="missing \\['event'\\]"):
        c.labels()                       # declared label omitted
    with pytest.raises(ValueError, match="extra \\['evnt'\\]"):
        c.labels(evnt="typo")            # misnamed label, right count


def test_submitted_instant_precedes_queue_visibility():
    """The submitted mark is emitted BEFORE the request becomes
    admittable, so a threaded serve() can never trace admitted ahead of
    submitted (reviewed race)."""
    tr = tracer()
    tr.clear()
    sched = ContinuousBatchingScheduler(FakeModel(), n_slots=1,
                                        max_new_tokens=2)
    sched.serve()
    try:
        reqs = [sched.submit([2, 3]) for _ in range(6)]
        for r in reqs:
            assert r.wait(timeout=60)
    finally:
        sched.shutdown()
    subs = {e["args"]["rid"]: e["ts"]
            for e in tr.events("request/submitted")}
    for e in tr.events("request/admitted"):
        assert subs[e["args"]["rid"]] <= e["ts"]


def test_master_server_metrics_and_statusz_attach():
    from paddle_tpu.parallel.master import TaskQueue
    from paddle_tpu.parallel.master_service import MasterServer

    q = TaskQueue()
    q.set_dataset(["a", "b", "c"])
    master = MasterServer(q)
    master.start()
    try:
        text = registry().render_prometheus()
        assert 'paddle_master_tasks{state="todo"}' in text
        srv = ObservabilityServer()
        srv.attach("master", master)
        addr = srv.start()
        try:
            status = json.loads(_get(addr, "/statusz"))
            assert status["master"]["todo"] == 3
        finally:
            srv.stop()
    finally:
        master.stop()


def test_obs_cli_roundtrip(tmp_path, capsys):
    from paddle_tpu.tools import obs

    tr = tracer()
    tr.instant("cli/mark")
    srv = ObservabilityServer()
    srv.attach("demo", lambda: {"x": 1})
    addr = srv.start()
    try:
        assert obs.main(["healthz", addr]) == 0
        assert '"ok": true' in capsys.readouterr().out

        assert obs.main(["metrics", addr,
                         "--grep", "paddle_serving"]) == 0
        out = capsys.readouterr().out
        assert all("paddle_serving" in ln
                   for ln in out.splitlines() if ln.strip())

        assert obs.main(["statusz", addr]) == 0
        assert json.loads(capsys.readouterr().out)["demo"] == {"x": 1}

        dump = tmp_path / "t.json"
        assert obs.main(["trace", addr, "-o", str(dump)]) == 0
        names = [e["name"]
                 for e in json.loads(dump.read_text())["traceEvents"]]
        assert "cli/mark" in names
    finally:
        srv.stop()
    # unreachable endpoint -> exit 2
    assert obs.main(["healthz", "127.0.0.1:1", "--timeout", "0.2"]) == 2


def test_guardrail_counters_exported_on_recovery():
    """A skipped non-finite step shows up both in health_stats() (the
    dict view) and the exported guardrail series + guard/skip trace
    instant — one signal, three faces."""
    from paddle_tpu.resilience import GuardPolicy

    tr = tracer()
    tr.clear()
    exe = fluid.Executor(fluid.CPUPlace())
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data("x", [2], "float32")
        y = fluid.layers.mean(fluid.layers.fc(input=x, size=2))
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        feed = {"x": np.array([[np.nan, 1.0]], np.float32)}
        exe.run(main, feed=feed, fetch_list=[y],
                guard=GuardPolicy(on_nonfinite="skip", check=("loss",)))
    assert exe.health_stats()["skips"] == 1
    text = registry().render_prometheus()
    m = re.search(
        r'paddle_guardrail_events_total\{event="skips"\} (\d+)', text)
    assert m and int(m.group(1)) >= 1
    assert len(tr.events("guard/skip")) == 1
