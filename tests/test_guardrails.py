"""Training guardrails (resilience/guardrails.py + Executor guard=...):
the fused finiteness sentinel, skip/rollback/raise/escalate recovery,
the hung-step watchdog, transient-fault retry, the chaos points that
drive them deterministically, and the ResilientTrainer/journal wiring.

Everything here is fast and seeded; the NaN-storm end-to-end run is
marked slow.
"""

import json
import os

import numpy as np
import pytest

from paddle_tpu import fluid
from paddle_tpu.parallel import TaskQueue
from paddle_tpu.resilience import (FaultInjector, GuardPolicy,
                                   NonFiniteError, NonFiniteEscalation,
                                   ResilientTrainer, RetryPolicy,
                                   StepTimeout, install)

PARAM_PREFIX = "fc_0"


def build_net(seed=7):
    """A deterministic fc regression step: -> (main, startup, scope,
    cost).  Per-program rng salts make two builds identical
    program-for-program (the bitwise comparisons depend on it)."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data("x", [4], "float32")
        y = fluid.layers.data("y", [1], "float32")
        pred = fluid.layers.fc(input=x, size=1)
        cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
    return main, startup, scope, cost


def clean_feed(seed=0):
    r = np.random.RandomState(seed)
    return {"x": r.rand(8, 4).astype(np.float32),
            "y": r.rand(8, 1).astype(np.float32)}


def bad_feed(value=np.nan, seed=0):
    feed = clean_feed(seed)
    feed["x"][0, 0] = value
    return feed


def params_of(scope):
    return {n: np.asarray(scope.find_var(n)).copy()
            for n in scope.vars if n.startswith(PARAM_PREFIX)}


def assert_bitwise_equal(a, b):
    assert set(a) == set(b)
    for n in a:
        assert a[n].tobytes() == b[n].tobytes(), f"{n} differs"


@pytest.fixture(autouse=True)
def _no_ambient_chaos():
    """Each test owns the process-global injector."""
    prev = install(None)
    yield
    install(prev)


def run_startup(exe, startup, scope):
    with fluid.scope_guard(scope):
        exe.run(startup)


# -- fused sentinel ----------------------------------------------------------

class TestSentinel:
    def test_clean_guarded_step_bitwise_identical_to_unguarded(self):
        """The acceptance contract: on healthy batches the guard's
        select-on-true publish and fused isfinite reductions change
        NOTHING — fetches and params are bitwise those of run()."""
        feed = clean_feed()
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        with fluid.scope_guard(scope):
            base_out, = exe.run(main, feed=feed, fetch_list=[cost])
        base_params = params_of(scope)

        for policy in (GuardPolicy("skip"), GuardPolicy("rollback"),
                       GuardPolicy("raise", check=("loss", "grads"))):
            m, st, sc, c = build_net()
            e = fluid.Executor(fluid.CPUPlace())
            run_startup(e, st, sc)
            with fluid.scope_guard(sc):
                out, = e.run(m, feed=feed, fetch_list=[c], guard=policy)
            assert np.asarray(out).tobytes() == np.asarray(base_out).tobytes()
            assert_bitwise_equal(base_params, params_of(sc))
            stats = e.health_stats()
            assert stats["guarded_steps"] == 1
            assert stats["nonfinite_steps"] == 0

    def test_guard_accepts_policy_string_shorthand(self):
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        with fluid.scope_guard(scope):
            exe.run(main, feed=clean_feed(), fetch_list=[cost], guard="skip")
        assert exe.health_stats()["guarded_steps"] == 1

    def test_sentinel_catches_inf_not_just_nan(self):
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        with fluid.scope_guard(scope):
            exe.run(main, feed=bad_feed(np.inf), fetch_list=[cost],
                    guard=GuardPolicy("skip"))
        assert exe.health_stats()["nonfinite_steps"] == 1

    def test_grads_only_check_catches_nonfinite_grad(self):
        """check=("grads",) alone must flag the step — the @GRAD vars
        feed the sentinel even when the fetched loss is finite-checked
        off."""
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        with fluid.scope_guard(scope):
            exe.run(main, feed=bad_feed(), fetch_list=[cost],
                    guard=GuardPolicy("skip", check=("grads",)))
        assert exe.health_stats()["nonfinite_steps"] == 1


# -- recovery policies -------------------------------------------------------

class TestRecovery:
    def test_skip_leaves_params_bitwise_unchanged(self):
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        pol = GuardPolicy("skip")
        with fluid.scope_guard(scope):
            exe.run(main, feed=clean_feed(), fetch_list=[cost], guard=pol)
            pre = params_of(scope)
            out, = exe.run(main, feed=bad_feed(), fetch_list=[cost],
                           guard=pol)
        assert not np.isfinite(float(out))   # fetches still report the step
        assert_bitwise_equal(pre, params_of(scope))
        stats = exe.health_stats()
        assert stats == {"guarded_steps": 2, "nonfinite_steps": 1,
                         "skips": 1, "rollbacks": 0, "escalations": 0,
                         "watchdog_fires": 0, "retries": 0}

    def test_raise_surfaces_nonfinite_with_pre_step_scope(self):
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        pre = params_of(scope)
        with fluid.scope_guard(scope):
            with pytest.raises(NonFiniteError):
                exe.run(main, feed=bad_feed(), fetch_list=[cost],
                        guard=GuardPolicy("raise"))
        assert_bitwise_equal(pre, params_of(scope))
        assert exe.health_stats()["nonfinite_steps"] == 1

    def test_rollback_restores_snapshot_from_k_steps_ago(self):
        """snapshot_every=3: the snapshot is taken before step 1 (the
        initialized params); steps 1-2 train on clean batches; the bad
        step 3 rolls the scope back to the SNAPSHOT — i.e. the init
        params, not merely the pre-step-3 params (that would be skip)."""
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        init = params_of(scope)
        pol = GuardPolicy("rollback", snapshot_every=3)
        with fluid.scope_guard(scope):
            exe.run(main, feed=clean_feed(0), fetch_list=[cost], guard=pol)
            exe.run(main, feed=clean_feed(1), fetch_list=[cost], guard=pol)
            pre_bad = params_of(scope)
            exe.run(main, feed=bad_feed(), fetch_list=[cost], guard=pol)
        post = params_of(scope)
        assert_bitwise_equal(init, post)
        # and it genuinely rewound past the pre-step state
        assert any(pre_bad[n].tobytes() != post[n].tobytes() for n in post)
        stats = exe.health_stats()
        assert stats["rollbacks"] == 1 and stats["nonfinite_steps"] == 1

    def test_rollback_snapshot_refreshes_on_cadence(self):
        """snapshot_every=1: every pre-step state is snapshotted, so a
        bad step restores exactly the pre-step params — and training
        continues cleanly afterwards (the snapshot copies survive the
        next dispatch's buffer donation)."""
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        pol = GuardPolicy("rollback", snapshot_every=1)
        with fluid.scope_guard(scope):
            exe.run(main, feed=clean_feed(0), fetch_list=[cost], guard=pol)
            pre = params_of(scope)
            exe.run(main, feed=bad_feed(), fetch_list=[cost], guard=pol)
            assert_bitwise_equal(pre, params_of(scope))
            out, = exe.run(main, feed=clean_feed(1), fetch_list=[cost],
                           guard=pol)
        assert np.isfinite(float(out))
        # the clean step after the rollback actually trained
        assert any(params_of(scope)[n].tobytes() != pre[n].tobytes()
                   for n in pre)
        assert exe.health_stats()["rollbacks"] == 1

    def test_escalation_after_m_consecutive_bad_steps(self):
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        pol = GuardPolicy("skip", escalate_after=2)
        with fluid.scope_guard(scope):
            exe.run(main, feed=bad_feed(), fetch_list=[cost], guard=pol)
            with pytest.raises(NonFiniteEscalation):
                exe.run(main, feed=bad_feed(), fetch_list=[cost], guard=pol)
            # a healthy step resets the consecutive counter
            exe.run(main, feed=clean_feed(), fetch_list=[cost], guard=pol)
            exe.run(main, feed=bad_feed(), fetch_list=[cost], guard=pol)
        stats = exe.health_stats()
        assert stats["escalations"] == 1
        assert stats["skips"] == 2          # bad steps 1 and 3 skipped
        assert stats["nonfinite_steps"] == 3

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            GuardPolicy("explode")
        with pytest.raises(ValueError):
            GuardPolicy("skip", check=("loss", "vibes"))
        with pytest.raises(ValueError):
            GuardPolicy("skip", check=())
        # 0 / negative are the conventional "watchdog off", never an
        # instant-fire deadline
        assert GuardPolicy("skip", step_timeout=0).step_timeout is None
        assert GuardPolicy("skip", step_timeout=-1).step_timeout is None
        assert GuardPolicy("skip", step_timeout=1.5).step_timeout == 1.5

    def test_skip_drops_write_only_persistables(self):
        """A persistable the program writes but never reads has no
        pre-step twin for the gate — a bad step must drop it rather
        than publish its non-finite value into the scope (where the
        next checkpoint would durably record it)."""
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), fluid.unique_name.guard():
            x = fluid.layers.data("x", [4], "float32")
            y = fluid.layers.data("y", [1], "float32")
            pred = fluid.layers.fc(input=x, size=1)
            cost = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            metric = fluid.layers.create_global_var(
                [], 0.0, "float32", persistable=True, name="last_cost")
            fluid.layers.assign(cost, metric)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        pol = GuardPolicy("skip")
        with fluid.scope_guard(scope):
            exe.run(main, feed=clean_feed(), fetch_list=[cost], guard=pol)
            good = float(np.asarray(scope.find_var("last_cost")))
            exe.run(main, feed=bad_feed(), fetch_list=[cost], guard=pol)
            after = float(np.asarray(scope.find_var("last_cost")))
        assert np.isfinite(good)
        assert after == good            # the poisoned write was dropped


# -- seeded chaos ------------------------------------------------------------

class TestChaos:
    def test_guard_nan_schedule_yields_exact_skip_count(self):
        """PADDLE_TPU_CHAOS guard.nan=p with a fixed seed: the fired
        draws are a pure function of (seed, point, index), so the skip
        counter after N steps equals the schedule's exact fire count."""
        seed, prob, steps = 3, 0.5, 6
        expected = sum(FaultInjector.decision(seed, "guard.nan", i) < prob
                       for i in range(steps))
        assert 0 < expected < steps      # a schedule that exercises both
        install(FaultInjector(spec=f"guard.nan={prob}", seed=seed))
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        pol = GuardPolicy("skip")
        with fluid.scope_guard(scope):
            for i in range(steps):
                exe.run(main, feed=clean_feed(i), fetch_list=[cost],
                        guard=pol)
        stats = exe.health_stats()
        assert stats["skips"] == expected
        assert stats["nonfinite_steps"] == expected
        assert stats["guarded_steps"] == steps
        for v in params_of(scope).values():
            assert np.isfinite(v).all()

    def test_guard_inf_grad_poisons_with_inf(self):
        install(FaultInjector(spec="guard.inf_grad=1.0", seed=1))
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        pre = params_of(scope)
        with fluid.scope_guard(scope):
            exe.run(main, feed=clean_feed(), fetch_list=[cost],
                    guard=GuardPolicy("skip"))
        assert_bitwise_equal(pre, params_of(scope))
        assert exe.health_stats()["skips"] == 1

    def test_chaos_points_inert_without_guard(self):
        """An unguarded run must not consume chaos draws or poison
        feeds — the guard points only exist on the guarded path."""
        install(FaultInjector(spec="guard.nan=1.0", seed=1))
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        with fluid.scope_guard(scope):
            out, = exe.run(main, feed=clean_feed(), fetch_list=[cost])
        assert np.isfinite(float(out))

    def test_watchdog_fires_within_deadline_on_injected_hang(self):
        import time

        install(FaultInjector(spec="guard.hang=1.0", seed=1,
                              hang_seconds=2.0))
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        with fluid.scope_guard(scope):
            # warm the executable so the deadline times the hang, not
            # the compile
            install(None)
            exe.run(main, feed=clean_feed(), fetch_list=[cost],
                    guard=GuardPolicy("skip", step_timeout=5.0))
            install(FaultInjector(spec="guard.hang=1.0", seed=1,
                                  hang_seconds=2.0))
            t0 = time.monotonic()
            with pytest.raises(StepTimeout):
                exe.run(main, feed=clean_feed(), fetch_list=[cost],
                        guard=GuardPolicy("skip", step_timeout=0.2))
            elapsed = time.monotonic() - t0
        assert elapsed < 1.5, "watchdog did not cut the 2s hang short"
        assert exe.health_stats()["watchdog_fires"] == 1

    def test_transient_fault_retried_successfully(self):
        """guard.fault raises a transient ChaosError on the first
        attempt and clears on the second (a probability straddling the
        two seeded draws): the retry policy re-dispatches and the step
        completes with the exact clean-run result."""
        d0 = FaultInjector.decision(0, "guard.fault", 0)
        d1 = FaultInjector.decision(0, "guard.fault", 1)
        assert d0 < d1                    # seed 0 straddles at p between
        prob = (d0 + d1) / 2
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        with fluid.scope_guard(scope):
            base, = exe.run(main, feed=clean_feed(), fetch_list=[cost])

        main2, startup2, scope2, cost2 = build_net()
        exe2 = fluid.Executor(fluid.CPUPlace())
        run_startup(exe2, startup2, scope2)
        install(FaultInjector(spec=f"guard.fault={prob}", seed=0))
        with fluid.scope_guard(scope2):
            out, = exe2.run(
                main2, feed=clean_feed(), fetch_list=[cost2],
                guard=GuardPolicy("skip", retry=RetryPolicy(
                    max_attempts=3, deadline=None, base_delay=0.001,
                    max_delay=0.002, seed=0)))
        assert np.asarray(out).tobytes() == np.asarray(base).tobytes()
        stats = exe2.health_stats()
        assert stats["retries"] == 1
        assert stats["guarded_steps"] == 1

    def test_hang_then_clear_is_retried_through_watchdog(self):
        """A one-off hang: the watchdog fires StepTimeout (transient),
        the retry re-dispatches, the second attempt has no hang and the
        step completes — watchdog_fires and retries each count 1."""
        seed = next(s for s in range(100)
                    if FaultInjector.decision(s, "guard.hang", 0)
                    < FaultInjector.decision(s, "guard.hang", 1))
        d0 = FaultInjector.decision(seed, "guard.hang", 0)
        d1 = FaultInjector.decision(seed, "guard.hang", 1)
        prob = (d0 + d1) / 2
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        with fluid.scope_guard(scope):
            # pre-compile outside the deadline
            exe.run(main, feed=clean_feed(), fetch_list=[cost],
                    guard=GuardPolicy("skip"))
        install(FaultInjector(spec=f"guard.hang={prob}", seed=seed,
                              hang_seconds=2.0))
        with fluid.scope_guard(scope):
            out, = exe.run(
                main, feed=clean_feed(1), fetch_list=[cost],
                guard=GuardPolicy("skip", step_timeout=0.2,
                                  retry=RetryPolicy(
                                      max_attempts=3, deadline=None,
                                      base_delay=0.001, max_delay=0.002,
                                      seed=0)))
        assert np.isfinite(float(out))
        stats = exe.health_stats()
        assert stats["watchdog_fires"] == 1
        assert stats["retries"] == 1

    def test_fatal_error_not_retried(self):
        """A non-transient dispatch error surfaces unchanged (and
        unretried) — retry must not paper over real bugs."""
        from paddle_tpu.resilience.guardrails import classify_step_error

        assert not classify_step_error(ValueError("shape mismatch"))
        assert classify_step_error(ConnectionError("reset"))
        assert classify_step_error(TimeoutError("deadline"))
        assert classify_step_error(StepTimeout("pre-device stall"))
        # a timeout AFTER the donated buffers were consumed must not
        # re-dispatch them under the still-running hung call
        assert not classify_step_error(
            StepTimeout("wedged in device", retry_safe=False))

    def test_consumed_timeout_is_not_retried(self):
        """A hang INSIDE the device call (ctl.consumed set) surfaces as
        a non-retryable StepTimeout on the first fire — the retry
        policy must not race the wedged dispatch for the donated
        buffers."""
        import time

        from paddle_tpu.resilience.guardrails import dispatch_guarded

        attempts = []

        def thunk(ctl):
            attempts.append(1)
            ctl.consumed = True           # "reached the device"
            time.sleep(0.5)               # ...and wedged there
            return "late"

        stats = {"watchdog_fires": 0, "retries": 0}
        pol = GuardPolicy("skip", step_timeout=0.05,
                          retry=RetryPolicy(max_attempts=5, deadline=None,
                                            base_delay=0.001,
                                            max_delay=0.002, seed=0))
        with pytest.raises(StepTimeout) as ei:
            dispatch_guarded(thunk, pol, stats)
        assert ei.value.retry_safe is False
        assert stats["watchdog_fires"] == 1
        assert stats["retries"] == 0 and len(attempts) == 1

    def test_abandoned_attempt_honors_cancellation(self):
        """A pre-device stall that outlives the deadline IS retried —
        and the abandoned worker sees ctl.cancelled and must not go on
        to consume the buffers the retry now owns."""
        import time

        from paddle_tpu.resilience.guardrails import (StepFault,
                                                      dispatch_guarded)

        consumed_by = []
        calls = {"n": 0}

        def thunk(ctl):
            calls["n"] += 1
            if calls["n"] == 1:           # first attempt: stall host-side
                time.sleep(0.3)
                if ctl.cancelled.is_set():
                    raise StepFault("abandoned")
            consumed_by.append(id(ctl))
            ctl.consumed = True
            return "ok"

        stats = {"watchdog_fires": 0, "retries": 0}
        pol = GuardPolicy("skip", step_timeout=0.05,
                          retry=RetryPolicy(max_attempts=3, deadline=None,
                                            base_delay=0.001,
                                            max_delay=0.002, seed=0))
        assert dispatch_guarded(thunk, pol, stats) == "ok"
        assert stats["watchdog_fires"] == 1 and stats["retries"] == 1
        time.sleep(0.4)                   # let the abandoned worker wake
        assert len(consumed_by) == 1      # it never consumed the buffers

    def test_consumed_transient_error_not_retried_but_structured(self):
        """A transient-shaped error raised AFTER the attempt claimed
        the donated buffers must not re-dispatch them — it surfaces
        once, wrapped as StepFault (so the executor republishes the
        rollback snapshot), with zero retries."""
        from paddle_tpu.resilience.guardrails import (StepFault,
                                                      dispatch_guarded)

        attempts = []

        def thunk(ctl):
            attempts.append(1)
            assert ctl.begin_consume()
            raise ConnectionError("UNAVAILABLE: device dropped mid-step")

        stats = {"watchdog_fires": 0, "retries": 0}
        pol = GuardPolicy("skip",
                          retry=RetryPolicy(max_attempts=5, deadline=None,
                                            base_delay=0.001,
                                            max_delay=0.002, seed=0))
        with pytest.raises(StepFault) as ei:
            dispatch_guarded(thunk, pol, stats)
        assert isinstance(ei.value.__cause__, ConnectionError)
        assert len(attempts) == 1 and stats["retries"] == 0

    def test_state_buffers_live_tracks_deletion(self):
        """jax.Array.is_deleted is the ground truth for whether a
        failed dispatch consumed the donated inputs."""
        import jax.numpy as jnp

        from paddle_tpu.resilience.guardrails import state_buffers_live

        a = jnp.ones((2, 2))
        state = {"w": a, "host": np.ones(3)}
        assert state_buffers_live(state)
        a.delete()
        assert not state_buffers_live(state)

    def test_device_fault_with_live_buffers_is_retried(self):
        """An error from inside the device call releases its buffer
        claim (unconsume) when every donated input is verifiably live —
        the PJRT-preemption retry path."""
        from paddle_tpu.resilience.guardrails import dispatch_guarded

        calls = {"n": 0}

        def thunk(ctl):
            calls["n"] += 1
            assert ctl.begin_consume()
            if calls["n"] == 1:
                ctl.unconsume()       # inputs verified live after failure
                raise ConnectionError("UNAVAILABLE: transient")
            return "ok"

        stats = {"watchdog_fires": 0, "retries": 0}
        pol = GuardPolicy("skip",
                          retry=RetryPolicy(max_attempts=3, deadline=None,
                                            base_delay=0.001,
                                            max_delay=0.002, seed=0))
        assert dispatch_guarded(thunk, pol, stats) == "ok"
        assert stats["retries"] == 1 and calls["n"] == 2

    def test_explicit_check_nan_inf_flag_survives_narrow_guard(self):
        """FLAGS check_nan_inf promises a raise on ANY non-finite; a
        guard watching only the loss must not silently disable it,
        while the full-check sentinel supersedes it."""
        from paddle_tpu.utils.flags import FLAGS

        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        old = FLAGS["check_nan_inf"]
        FLAGS["check_nan_inf"] = True
        try:
            with fluid.scope_guard(scope):
                # full check set: sentinel supersedes, skip absorbs
                exe.run(main, feed=bad_feed(), fetch_list=[cost],
                        guard=GuardPolicy("skip"))
                # narrow check set: the explicit global scan still runs
                with pytest.raises(FloatingPointError):
                    exe.run(main, feed=bad_feed(), fetch_list=[cost],
                            guard=GuardPolicy("skip", check=("loss",)))
        finally:
            FLAGS["check_nan_inf"] = old

    def test_guard_ctx_is_per_scope(self):
        """A rollback snapshot taken against one scope must never be
        republished into another: switching scopes resets the guard
        context, and the rollback restores the NEW scope's own
        last-good state."""
        main, startup, scope_a, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope_a)
        pol = GuardPolicy("rollback", snapshot_every=100)
        with fluid.scope_guard(scope_a):
            exe.run(main, feed=clean_feed(), fetch_list=[cost], guard=pol)

        scope_b = fluid.Scope()
        with fluid.scope_guard(scope_b):
            exe.run(startup)
            # make B's params unmistakably different from A's
            for n in list(scope_b.vars):
                if n.startswith(PARAM_PREFIX):
                    scope_b.set_var(
                        n, np.asarray(scope_b.find_var(n)) + 7.0)
            b_init = params_of(scope_b)
            exe.run(main, feed=clean_feed(), fetch_list=[cost], guard=pol)
            exe.run(main, feed=bad_feed(), fetch_list=[cost], guard=pol)
        post = params_of(scope_b)
        # rolled back to B's snapshot (its shifted init), not A's
        assert_bitwise_equal(b_init, post)

    def test_alternating_scopes_keep_separate_guard_contexts(self):
        """Two models (same program, two scopes, one executor) run
        guarded steps alternately: each keeps its own escalation
        counter — the context is keyed per (program, scope), not
        clobbered on every alternation."""
        main, startup, scope_a, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope_a)
        scope_b = fluid.Scope()
        with fluid.scope_guard(scope_b):
            exe.run(startup)
        pol = GuardPolicy("skip", escalate_after=2)
        # interleave: A-bad, B-clean, A-bad -> A escalates on its 2nd
        # consecutive bad step despite B's healthy step in between
        with fluid.scope_guard(scope_a):
            exe.run(main, feed=bad_feed(), fetch_list=[cost], guard=pol)
        with fluid.scope_guard(scope_b):
            exe.run(main, feed=clean_feed(), fetch_list=[cost], guard=pol)
        with fluid.scope_guard(scope_a):
            with pytest.raises(NonFiniteEscalation):
                exe.run(main, feed=bad_feed(), fetch_list=[cost],
                        guard=pol)
        assert exe.health_stats()["escalations"] == 1

    def test_timeout_escape_republishes_rollback_snapshot(self):
        """A watchdog fire under a rollback policy leaves the scope
        holding the last-good snapshot (fresh never-donated copies) —
        the documented survival story for a wedged device."""
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        run_startup(exe, startup, scope)
        pol = GuardPolicy("rollback", snapshot_every=1, step_timeout=2.0)
        with fluid.scope_guard(scope):
            # first step also pays the XLA compile — keep it outside
            # the tight deadline used for the hang below
            exe.run(main, feed=clean_feed(), fetch_list=[cost], guard=pol)
            pre = params_of(scope)
            pol = GuardPolicy("rollback", snapshot_every=1,
                              step_timeout=0.2)
            install(FaultInjector(spec="guard.hang=1.0", seed=1,
                                  hang_seconds=1.5))
            with pytest.raises(StepTimeout):
                exe.run(main, feed=clean_feed(1), fetch_list=[cost],
                        guard=pol)
            install(None)
        assert_bitwise_equal(pre, params_of(scope))
        # and the scope is live: the next guarded step trains normally
        with fluid.scope_guard(scope):
            out, = exe.run(main, feed=clean_feed(2), fetch_list=[cost],
                           guard=pol)
        assert np.isfinite(float(out))


# -- trainer integration -----------------------------------------------------

def _guarded_trainer(tmp_path, q, policy, bad_records, max_steps=None,
                     escalate_after=0):
    """Drive ResilientTrainer over a NaN-poisoned record stream with a
    guarded train_step; returns (trainer, final step, scope, cost
    history)."""
    main, startup, scope, cost = build_net()
    exe = fluid.Executor(fluid.CPUPlace())
    costs = []

    def read_chunk(seed):
        r = np.random.RandomState(seed)
        out = []
        for j in range(4):
            xs = r.rand(8, 4).astype(np.float32)
            ys = r.rand(8, 1).astype(np.float32)
            if (seed, j) in bad_records:
                xs[0, 0] = np.nan
            out.append((xs, ys))
        return out

    def train_step(rec, step):
        out, = exe.run(main, feed={"x": rec[0], "y": rec[1]},
                       fetch_list=[cost], guard=policy)
        costs.append(float(np.asarray(out)))

    trainer = ResilientTrainer(str(tmp_path), q, read_chunk,
                               program=main, scope=scope,
                               save_interval_steps=2, poll_interval=0.02,
                               guard=policy, guard_executor=exe)
    with fluid.scope_guard(scope):
        final = trainer.run(train_step, init_fn=lambda: exe.run(startup),
                            max_steps=max_steps)
    return trainer, final, scope, costs, exe


def test_trainer_journals_skipped_batches(tmp_path):
    q = TaskQueue(timeout_secs=30)
    q.set_dataset([0, 1])
    trainer, final, scope, costs, exe = _guarded_trainer(
        tmp_path, q, GuardPolicy("skip"), bad_records={(0, 1), (1, 2)})
    assert q.all_done() and final == 8
    assert exe.health_stats()["skips"] == 2
    lines = [json.loads(ln) for ln in
             open(trainer.guard_journal_path())]
    assert [ln["event"] for ln in lines] == ["skip", "skip"]
    assert all(ln["count"] == 1 for ln in lines)
    for v in params_of(scope).values():
        assert np.isfinite(v).all()


def test_trainer_escalation_restores_checkpoint_and_continues(tmp_path):
    """escalate_after=1: the first bad batch raises NonFiniteEscalation
    out of the guarded run; the trainer answers with
    CheckpointManager.restore(), journals it, and keeps draining the
    queue — the lease is never failed."""
    q = TaskQueue(timeout_secs=30)
    q.set_dataset([0, 1])
    trainer, final, scope, costs, exe = _guarded_trainer(
        tmp_path, q, GuardPolicy("skip", escalate_after=1),
        bad_records={(1, 1)})
    assert q.all_done() and q.counts()["failed"] == 0
    assert exe.health_stats()["escalations"] == 1
    events = [json.loads(ln)["event"]
              for ln in open(trainer.guard_journal_path())]
    assert "escalate-restore" in events
    restored = [json.loads(ln) for ln in open(trainer.guard_journal_path())
                if json.loads(ln)["event"] == "escalate-restore"]
    assert restored[0]["restored_step"] is not None
    for v in params_of(scope).values():
        assert np.isfinite(v).all()


def test_trainer_escalation_without_checkpoint_propagates(tmp_path):
    """A storm before the first save has nothing to restore: the
    escalation must surface (charging the lease) instead of silently
    draining the queue while training on nothing."""
    q = TaskQueue(timeout_secs=30, failure_max=1)
    q.set_dataset([0])
    with pytest.raises(NonFiniteEscalation):
        _guarded_trainer(tmp_path, q,
                         GuardPolicy("skip", escalate_after=1),
                         bad_records={(0, 0)})   # very first record
    assert q.counts()["failed"] == 1             # lease charged


@pytest.mark.slow
def test_nan_storm_end_to_end(tmp_path):
    """A chaos NaN storm mid-training under ResilientTrainer: the run
    completes, the loss still decreases, the journal records the
    skipped batches, and the final parameters are finite."""
    steps = 40
    prob, seed = 0.3, 11
    expected = sum(FaultInjector.decision(seed, "guard.nan", i) < prob
                   for i in range(steps))
    assert expected > 0
    install(FaultInjector(spec=f"guard.nan={prob}", seed=seed))
    try:
        main, startup, scope, cost = build_net()
        exe = fluid.Executor(fluid.CPUPlace())
        costs = []
        W = np.array([1.0, -2.0, 0.5, 3.0], np.float32)

        def read_chunk(seed_):
            r = np.random.RandomState(seed_)
            out = []
            for _ in range(10):
                xs = r.randn(8, 4).astype(np.float32)
                out.append((xs, xs @ W[:, None]))
            return out

        policy = GuardPolicy("skip")

        def train_step(rec, step):
            out, = exe.run(main, feed={"x": rec[0], "y": rec[1]},
                           fetch_list=[cost], guard=policy)
            c = float(np.asarray(out))
            if np.isfinite(c):
                costs.append(c)

        q = TaskQueue(timeout_secs=30)
        q.set_dataset(list(range(4)))
        trainer = ResilientTrainer(str(tmp_path), q, read_chunk,
                                   program=main, scope=scope,
                                   save_interval_steps=5,
                                   poll_interval=0.02,
                                   guard=policy, guard_executor=exe)
        with fluid.scope_guard(scope):
            final = trainer.run(train_step,
                                init_fn=lambda: exe.run(startup))
        assert final == steps and q.all_done()
        stats = exe.health_stats()
        assert stats["skips"] == expected
        assert stats["guarded_steps"] == steps
        skipped = sum(json.loads(ln)["count"]
                      for ln in open(trainer.guard_journal_path()))
        assert skipped == expected
        assert costs[-1] < costs[0] * 0.5, (costs[0], costs[-1])
        for v in params_of(scope).values():
            assert np.isfinite(v).all()
    finally:
        install(None)


# -- error clip (satellite) --------------------------------------------------

class TestErrorClip:
    def test_error_clip_bounds_upstream_gradient(self):
        """var.error_clip = ErrorClipByValue(max): the gradient flowing
        upstream from that var is clamped to [min, max] during
        append_backward (reference clip.py semantics)."""
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 5
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), fluid.unique_name.guard():
            x = fluid.layers.data("x", [4], "float32")
            y = fluid.layers.data("y", [1], "float32")
            hidden = fluid.layers.fc(input=x, size=8)
            hidden.error_clip = fluid.clip.ErrorClipByValue(max=1e-3)
            pred = fluid.layers.fc(input=hidden, size=1)
            cost = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            # 100x scale guarantees unclipped grads exceed the bound
            big = fluid.layers.scale(cost, scale=100.0)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(big)
        exe = fluid.Executor(fluid.CPUPlace())
        r = np.random.RandomState(0)
        feed = {"x": r.rand(16, 4).astype(np.float32),
                "y": r.rand(16, 1).astype(np.float32)}
        with fluid.scope_guard(scope):
            exe.run(startup)
            g, = exe.run(main, feed=feed,
                         fetch_list=[hidden.grad_name],
                         return_numpy=True)
        g = np.asarray(g)
        assert np.abs(g).max() <= 1e-3 + 1e-9
        # the clip actually bit: some entries sit exactly at the bound
        assert np.isclose(np.abs(g).max(), 1e-3)

    def test_error_clip_asymmetric_bounds(self):
        clip = fluid.clip.ErrorClipByValue(max=0.5, min=-0.1)
        assert clip.max == 0.5 and clip.min == -0.1
        with pytest.raises(ValueError):
            fluid.clip.ErrorClipByValue(max=-1.0, min=1.0)

    def test_error_clip_rejects_wrong_type(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup), fluid.unique_name.guard():
            x = fluid.layers.data("x", [4], "float32")
            h = fluid.layers.fc(input=x, size=2)
            h.error_clip = "not a clip"
            cost = fluid.layers.mean(h)
            with pytest.raises(TypeError):
                fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)

    def test_no_error_clip_means_no_clip_ops(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup), fluid.unique_name.guard():
            x = fluid.layers.data("x", [4], "float32")
            h = fluid.layers.fc(input=x, size=2)
            cost = fluid.layers.mean(h)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
        assert not [op for op in main.global_block().ops
                    if op.type == "clip"]


# -- checkpoint durability (satellite) ---------------------------------------

def test_checkpoint_save_fsyncs_every_file_before_publish(tmp_path,
                                                          monkeypatch):
    """save() must fsync each tensor file + META + the tmp directory
    BEFORE the publish rename: the rename may not become durable ahead
    of the bytes it names."""
    from paddle_tpu.fluid.checkpoint import CheckpointManager

    main, startup, scope, cost = build_net()
    exe = fluid.Executor(fluid.CPUPlace())
    run_startup(exe, startup, scope)

    synced_fds = []
    renames = []
    real_fsync, real_rename = os.fsync, os.rename

    def spy_fsync(fd):
        synced_fds.append(fd)
        return real_fsync(fd)

    def spy_rename(src, dst):
        renames.append((len(synced_fds), src, dst))
        return real_rename(src, dst)

    monkeypatch.setattr(os, "fsync", spy_fsync)
    monkeypatch.setattr(os, "rename", spy_rename)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    with fluid.scope_guard(scope):
        assert mgr.save(1, main, scope, force=True)
    n_files = len(os.listdir(tmp_path / "ck" / "ckpt-1"))  # tensors + META
    assert n_files >= 3
    # the publish rename happened...
    publish = [r for r in renames if r[2].endswith("ckpt-1")]
    assert len(publish) == 1
    # ...strictly after >= one fsync per file written + the tmp dir
    assert publish[0][0] >= n_files + 1
    # and the checkpoint round-trips
    fresh = fluid.Scope()
    assert mgr.restore(main, fresh) == 1
    for n, v in params_of(scope).items():
        assert np.asarray(fresh.find_var(n)).tobytes() == v.tobytes()


# -- layers.isfinite + guarded pipeline --------------------------------------

def test_layers_isfinite_in_program(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.layers.data("x", [3], "float32")
    flag = fluid.layers.isfinite(x)
    exe = fluid.Executor(fluid.CPUPlace())
    ok, = exe.run(main, feed={"x": np.ones((2, 3), np.float32)},
                  fetch_list=[flag])
    assert bool(np.asarray(ok)) is True
    bad = np.ones((2, 3), np.float32)
    bad[1, 2] = np.inf
    notok, = exe.run(main, feed={"x": bad}, fetch_list=[flag])
    assert bool(np.asarray(notok)) is False


def test_run_pipeline_threads_guard(tmp_path):
    """run_pipeline(guard=...) guards every step: a poisoned batch in
    the stream is skipped and the loop keeps going."""
    main, startup, scope, cost = build_net()
    exe = fluid.Executor(fluid.CPUPlace())
    run_startup(exe, startup, scope)
    feeds = [clean_feed(0), bad_feed(), clean_feed(1)]
    with fluid.scope_guard(scope):
        outs = exe.run_pipeline(main, iter(feeds), fetch_list=[cost],
                                guard=GuardPolicy("skip"))
    assert len(outs) == 3
    assert np.isfinite(float(outs[0][0]))
    assert not np.isfinite(float(outs[1][0]))
    assert np.isfinite(float(outs[2][0]))
    assert exe.health_stats()["skips"] == 1


def test_v2_sgd_train_guard(tmp_path):
    """v2 SGD.train(guard=...): a NaN batch mid-pass is skipped, the
    pass completes, and trainer.health_stats() reports it."""
    import paddle_tpu.v2 as paddle

    paddle.init(use_gpu=False, trainer_count=1, seed=7)
    images = paddle.layer.data(name="x",
                               type=paddle.data_type.dense_vector(4))
    label = paddle.layer.data(name="y",
                              type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=images, size=1,
                           act=paddle.activation.Linear())
    cost = paddle.layer.mse_cost(input=pred, label=label)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Momentum(learning_rate=0.01,
                                                  momentum=0.9))

    r = np.random.RandomState(0)
    batches = []
    for i in range(5):
        xs = r.rand(4, 4).astype(np.float32)
        if i == 2:
            xs[0, 0] = np.nan
        batches.append([(x, y) for x, y in
                        zip(xs, r.rand(4, 1).astype(np.float32))])

    def reader():
        return iter(batches)

    seen = []

    def handler(evt):
        if isinstance(evt, paddle.event.EndIteration):
            seen.append(evt.cost)

    trainer.train(reader, num_passes=1, event_handler=handler,
                  feeding={"x": 0, "y": 1}, prefetch=0,
                  guard=GuardPolicy("skip"))
    assert len(seen) == 5
    assert not np.isfinite(seen[2])
    assert all(np.isfinite(c) for i, c in enumerate(seen) if i != 2)
    stats = trainer.health_stats()
    assert stats["skips"] == 1 and stats["guarded_steps"] == 5
