"""IR + framework tests — mirror of the reference's framework unit tests
(paddle/framework/program_desc_test.cc, op_desc tests, python
test_program.py / test_operator_desc.py / test_variable.py)."""

import numpy as np
import pytest

from paddle_tpu import fluid
from paddle_tpu.fluid.core.desc import OpDesc, ProgramDesc, VarDesc


def test_desc_roundtrip():
    p = ProgramDesc()
    b = p.global_block()
    b.add_var(VarDesc("x", shape=[-1, 4], dtype="float32"))
    b.add_var(VarDesc("w", shape=[4, 3], persistable=True))
    b.add_var(VarDesc("y", shape=[-1, 3]))
    b.append_op(OpDesc("mul", {"X": ["x"], "Y": ["w"]}, {"Out": ["y"]},
                       {"x_num_col_dims": 1}))
    sub = p.append_block(0)
    sub.append_op(OpDesc("relu", {"X": ["y"]}, {"Out": ["y2"]}))
    op = b.ops[0]
    op.set_block_attr("sub_block", sub.idx)

    data = p.serialize_to_string()
    q = ProgramDesc.parse_from_string(data)
    assert q.serialize_to_string() == data
    assert q.fingerprint() == p.fingerprint()
    assert q.global_block().var("w").persistable
    assert q.global_block().ops[0].block_attr("sub_block") == 1
    assert q.global_block().ops[0].input("X") == ["x"]


def test_program_build_and_shape_inference(fresh_programs):
    main, startup, _ = fresh_programs
    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y = fluid.layers.fc(input=x, size=7, act="relu")
    assert x.shape == (-1, 13)
    assert y.shape == (-1, 7)
    loss = fluid.layers.mean(y)
    assert loss.shape == ()
    # parameters were created in both programs with initializer ops
    params = main.global_block().all_parameters()
    assert {tuple(p.shape) for p in params} == {(13, 7), (7,)}
    assert len(startup.global_block().ops) == 2


def test_program_clone_preserves_params(fresh_programs):
    main, startup, _ = fresh_programs
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    h = fluid.layers.fc(input=x, size=3)
    h = fluid.layers.dropout(h, dropout_prob=0.5)
    clone = main.clone(for_test=True)
    drop_ops = [op for op in clone.global_block().ops
                if op.type == "dropout"]
    assert drop_ops and drop_ops[0].attr("is_test") is True
    # original untouched
    orig = [op for op in main.global_block().ops if op.type == "dropout"]
    assert not orig[0].attr("is_test", False)
    assert clone.global_block().all_parameters()


def test_variable_lookup_parent_block(fresh_programs):
    main, _, _ = fresh_programs
    g = main.global_block()
    v = g.create_var(name="gvar", shape=[2], dtype="float32")
    sub = main.create_block()
    assert sub.var("gvar") is v
    main.rollback()
    assert main.current_block() is g


def test_registry_rejects_duplicate():
    from paddle_tpu.fluid.core.registry import OpInfo, register

    with pytest.raises(ValueError):
        register(OpInfo("relu", lambda ctx, ins: ins))


def test_op_attrs_and_unique_names(fresh_programs):
    main, _, _ = fresh_programs
    a = fluid.layers.data(name="a", shape=[4], dtype="float32")
    s1 = fluid.layers.scale(a, scale=3.0)
    s2 = fluid.layers.scale(a, scale=4.0)
    assert s1.name != s2.name
    ops = [op for op in main.global_block().ops if op.type == "scale"]
    assert ops[0].attr("scale") == 3.0 and ops[1].attr("scale") == 4.0
