"""Speculative + constrained decoding tests (ISSUE 15).

The load-bearing property is PARITY: whatever the draft model proposes
and whatever fraction of it the target accepts, the emitted tokens are
exactly what plain greedy decoding of the target would have produced —
speculation only changes how many target dispatches the tokens cost.
Everything else hangs off that: accept/reject rollback is host-side
page-table truncation (invariant-checked under prefix sharing and
copy-on-write), constraints mask both models' logits in-graph so
outputs always satisfy the grammar, mixed speculative/plain traffic
shares one verify executable with zero recompiles, and the gateway
carries draft/constraint options per request through the journal."""

import os

import numpy as np
import pytest

from paddle_tpu import fluid
from paddle_tpu.serving import (ContinuousBatchingScheduler,
                                PagedTransformerGenerator,
                                PoolCapacityError, SpeculativeGenerator,
                                copy_weights)
from paddle_tpu.serving.constraints import (DFAConstraint, MASKED,
                                            TokenSetConstraint,
                                            compile_constraint)
from paddle_tpu.serving.gateway import Gateway, ModelRegistry

V, NL, NH, DK, DM, DI = 24, 2, 2, 4, 16, 32
SRC, OUT, PS, CHUNK = 8, 8, 4, 4
END = 1

KW = dict(n_layer=NL, n_head=NH, d_key=DK, d_value=DK, d_model=DM,
          d_inner_hid=DI, max_length=64, src_len=SRC, max_out_len=OUT,
          page_size=PS, chunk_size=CHUNK, num_pages=64)


@pytest.fixture(scope="module")
def spec_pair():
    """(speculative generator with draft == target, the bare target,
    a mismatched-draft speculative generator) over one scope.  The
    identical-weight draft is the accept-rate-1.0 configuration; the
    reseeded draft disagrees almost always — parity must hold for
    both."""
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    kw = dict(KW, scope=scope, executor=exe)
    target = PagedTransformerGenerator(V, V, param_prefix="tgt", **kw)
    same = PagedTransformerGenerator(V, V, param_prefix="dsame", **kw)
    other = PagedTransformerGenerator(V, V, param_prefix="dother", **kw)
    target.init_params(seed=7)
    copy_weights(scope, scope, prefix="tgt", dst_prefix="dsame")
    with fluid.scope_guard(scope):
        other._unified[1].random_seed = 99
        exe.run(other._unified[1])
    spec = SpeculativeGenerator(target, same, k=3, draft_name="dsame")
    spec_mm = SpeculativeGenerator(target, other, k=3,
                                   draft_name="dother")
    return spec, target, spec_mm


def _sources(seed=0, n=4):
    rng = np.random.RandomState(seed)
    seqs = [rng.randint(2, V, rng.randint(3, SRC + 1)) for _ in range(n)]
    src = np.zeros((n, SRC), np.int64)
    lens = np.zeros(n, np.int32)
    for i, s in enumerate(seqs):
        src[i, :len(s)] = s
        lens[i] = len(s)
    return seqs, src, lens


def _trunc_at_end(row):
    row = [int(t) for t in row]
    return row[:row.index(END) + 1] if END in row else row


# -- parity -------------------------------------------------------------------

def test_draft_equals_target_parity_accept_one(spec_pair):
    """draft == target: every draft token verifies, accept rate is
    exactly 1.0, output is token-for-token the plain paged greedy, and
    the whole batch costs ~max_new/(k+1) verify dispatches."""
    spec, target, _ = spec_pair
    _, src, lens = _sources(seed=0)
    ref = target.greedy(src, lens, max_new=OUT, stop_at_end=False)
    v0 = spec.cache_stats()["speculative"]["verify_steps"]
    out = spec.greedy(src, lens, max_new=OUT, stop_at_end=False)
    np.testing.assert_array_equal(ref, out)
    st = spec.cache_stats()["speculative"]
    assert st["accept_rate"] == 1.0
    # 8 tokens at k=3 -> ceil(8/4)+1(prefill rides) verify dispatches,
    # far under the 8 a plain path pays; bound it loosely
    assert st["verify_steps"] - v0 <= OUT // 2 + 2
    # dense stop-at-end semantics survive the multi-token rounds
    ref_e = target.greedy(src, lens, max_new=OUT, stop_at_end=True)
    out_e = spec.greedy(src, lens, max_new=OUT, stop_at_end=True)
    np.testing.assert_array_equal(ref_e, out_e)


def test_mismatched_draft_still_exact(spec_pair):
    """A draft that disagrees with the target must cost speed, never
    correctness: rejected tokens roll back by position truncation and
    the emitted sequence is still exactly the target's greedy."""
    spec, target, spec_mm = spec_pair
    _, src, lens = _sources(seed=1)
    ref = target.greedy(src, lens, max_new=OUT, stop_at_end=False)
    out = spec_mm.greedy(src, lens, max_new=OUT, stop_at_end=False)
    np.testing.assert_array_equal(ref, out)
    st = spec_mm.cache_stats()["speculative"]
    assert st["drafted"] > 0 and st["accept_rate"] < 1.0
    spec_mm.check_invariants()


def test_speculation_disabled_parity(spec_pair):
    """decode={"draft": False} lanes ride the verify executable as
    plain 1-token decode — same tokens, no draft dispatches for them."""
    spec, target, _ = spec_pair
    _, src, lens = _sources(seed=2)
    ref = target.greedy(src, lens, max_new=OUT, stop_at_end=False)
    d0 = spec.cache_stats()["speculative"]["draft_steps"]
    out = spec.greedy(src, lens, max_new=OUT, stop_at_end=False,
                      speculative=False)
    np.testing.assert_array_equal(ref, out)
    # the draft ran only its (cheap) prefill-less idle dispatches: no
    # lane ever drafted, so no drafted tokens were recorded
    assert spec.cache_stats()["speculative"]["draft_steps"] == d0


def test_zero_recompiles_across_speculative_traffic(spec_pair):
    """After one warm batch, further mixed traffic adds no executable
    misses on EITHER program — the zero-recompile contract covers the
    draft and verify executables."""
    spec, _, _ = spec_pair
    _, src, lens = _sources(seed=3)
    spec.greedy(src, lens, max_new=OUT, stop_at_end=False)
    c0 = spec.cache_stats()
    _, src2, lens2 = _sources(seed=4)
    spec.greedy(src2, lens2, max_new=OUT, stop_at_end=False)
    spec.greedy(src2, lens2, max_new=OUT, stop_at_end=False,
                speculative=False)
    c1 = spec.cache_stats()
    assert c1["executable"]["misses"] == c0["executable"]["misses"]
    assert c1["draft_executable"]["misses"] == \
        c0["draft_executable"]["misses"]


# -- rollback / COW / invariants ---------------------------------------------

def test_rollback_truncation_under_prefix_sharing(spec_pair):
    """Speculative rounds over lanes whose prompts SHARE prefix-cached
    chunks: verification writes only lane-owned self pages (shared
    enc/cross pages are read-only on the decode path), rollback is pure
    position truncation, and the allocator invariants hold after every
    round."""
    spec, target, spec_mm = spec_pair
    rng = np.random.RandomState(5)
    base = rng.randint(2, V, SRC)        # one full-page shared prefix
    n = 3
    src = np.tile(base, (n, 1)).astype(np.int64)
    src[1:, PS:] = rng.randint(2, V, (n - 1, SRC - PS))
    lens = np.full(n, SRC, np.int32)
    ref = target.greedy(src, lens, max_new=OUT, stop_at_end=False)

    spec_mm.open_slots(n)
    hits0 = spec_mm.target.alloc.stats()["prefix_hits"]
    spec_mm.admit_slot(0, src[0], max_new=OUT)
    out = [[] for _ in range(n)]
    # let lane 0's prefill finish (its full chunks enter the prefix
    # cache), THEN admit the sharers: their admissions HIT the cached
    # chunk, so the shared enc/cross pages carry refcount > 1 while
    # speculative rounds verify and roll back over them
    while spec_mm.target._lanes[0].phase == "prefill":
        spec_mm.lane_step()
    for i in range(1, n):
        spec_mm.admit_slot(i, src[i], max_new=OUT)
    assert spec_mm.target.alloc.stats()["prefix_hits"] > hits0
    while any(len(o) < OUT for o in out):
        for slot, toks in spec_mm.lane_step().items():
            out[slot].extend(toks)
        spec_mm.check_invariants()       # after EVERY round
    for i in range(n):
        spec_mm.clear_slot(i)
    spec_mm.check_invariants()
    np.testing.assert_array_equal(
        ref, np.asarray([o[:OUT] for o in out], np.int64))


def test_cow_shared_self_page_not_mutated(spec_pair):
    """A self page some other holder still references is COW-copied
    BEFORE the verify dispatch writes: the shared bytes stay identical,
    the lane continues on its private copy, refcounts stay exact."""
    spec, target, _ = spec_pair
    seqs, _, _ = _sources(seed=6, n=1)
    spec.open_slots(1)
    spec.admit_slot(0, seqs[0], max_new=OUT)
    while spec.target._lanes[0].phase == "prefill" or \
            spec.draft._lanes[0].phase == "prefill":
        spec.lane_step()
    tl = spec.target._lanes[0]
    shared = tl.self_table[0]
    spec.target.alloc.ref(shared)        # an external holder appears
    cow0 = spec.cache_stats()["speculative"]["cow_copies"]
    pool_before = np.asarray(
        target.scope.find_var("tgt@kv_pool")).copy()
    spec.lane_step()
    assert tl.self_table[0] != shared
    assert spec.cache_stats()["speculative"]["cow_copies"] == cow0 + 1
    spec.check_invariants()
    rows = np.arange(2 * NL) + shared * 2 * NL
    pool_after = np.asarray(target.scope.find_var("tgt@kv_pool"))
    np.testing.assert_array_equal(pool_before[:, rows],
                                  pool_after[:, rows])
    spec.target.alloc.unref(shared)
    spec.clear_slot(0)
    spec.check_invariants()


def test_cow_pool_exhaustion_aborts_before_surgery(spec_pair):
    """A pool-capacity failure allocating COW copies must abort the
    round BEFORE any page-table surgery — a partially-committed COW
    would leave a lane pointing at a never-copied page and silently
    decode from garbage K/V.  The table is untouched, invariants hold,
    and the shared page's bytes survive."""
    spec, target, _ = spec_pair
    seqs, _, _ = _sources(seed=13, n=1)
    spec.open_slots(1)
    spec.admit_slot(0, seqs[0], max_new=OUT)
    while spec.target._lanes[0].phase == "prefill" or \
            spec.draft._lanes[0].phase == "prefill":
        spec.lane_step()
    alloc = spec.target.alloc
    tl = spec.target._lanes[0]
    shared = tl.self_table[0]
    alloc.ref(shared)                    # external holder forces COW
    hog = []                             # drain free AND evictable
    try:
        while True:
            try:
                hog.extend(alloc.alloc(1))
            except PoolCapacityError:
                break
        table_before = list(tl.self_table)
        pool_before = np.asarray(
            target.scope.find_var("tgt@kv_pool")).copy()
        with pytest.raises(PoolCapacityError):
            spec.lane_step()
        assert list(tl.self_table) == table_before   # no surgery
        spec.check_invariants()
        rows = np.arange(2 * NL) + shared * 2 * NL
        np.testing.assert_array_equal(
            pool_before[:, rows],
            np.asarray(target.scope.find_var("tgt@kv_pool"))[:, rows])
    finally:
        for p in hog:
            alloc.unref(p)
        alloc.unref(shared)
        spec.clear_slot(0)
    spec.check_invariants()


def test_rollback_to_continuation_parity(spec_pair):
    """Explicit rollback_to: truncate to an earlier committed point and
    keep decoding — the continuation re-derives exactly the tokens the
    first pass produced (greedy is a function of the committed
    prefix)."""
    spec, _, _ = spec_pair
    seqs, _, _ = _sources(seed=7, n=1)
    spec.open_slots(1)
    spec.admit_slot(0, seqs[0], max_new=OUT)
    got = []
    while len(got) < 5:
        for _, toks in spec.lane_step().items():
            got.extend(toks)
    spec.rollback_to(0, 2, got[1])
    tl = spec.target._lanes[0]
    assert (tl.pos, tl.cur) == (2, got[1])
    cont = []
    while len(cont) < 3:
        for _, toks in spec.lane_step().items():
            cont.extend(toks)
    assert cont[:3] == got[2:5]
    spec.clear_slot(0)
    spec.check_invariants()


def test_admit_draft_pool_refusal_releases_target_pages():
    """All-or-nothing admission: a draft pool too small for the request
    refuses the admit AND releases the pages the target half already
    took."""
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    kw = dict(KW, scope=scope, executor=exe)
    target = PagedTransformerGenerator(V, V, param_prefix="tp", **kw)
    tiny = PagedTransformerGenerator(
        V, V, param_prefix="dp", **dict(kw, num_pages=4))
    target.init_params(seed=0)
    copy_weights(scope, scope, prefix="tp", dst_prefix="dp")
    spec = SpeculativeGenerator(target, tiny, k=2)
    spec.open_slots(1)
    free_before = target.alloc.available()
    with pytest.raises(PoolCapacityError):
        spec.admit_slot(0, np.arange(2, 2 + SRC), max_new=OUT)
    assert target.alloc.available() == free_before
    spec.check_invariants()


# -- constraints --------------------------------------------------------------

def test_constrained_outputs_satisfy_token_set(spec_pair):
    """Every emitted token of a token_set-constrained request is in the
    allowed set (+ end), speculative or not, and the two modes agree
    token for token."""
    spec, _, _ = spec_pair
    _, src, lens = _sources(seed=8)
    allowed = {4, 5, 6}
    c = {"type": "token_set", "allowed": sorted(allowed)}
    out = spec.greedy(src, lens, max_new=OUT, stop_at_end=False,
                      constraint=c)
    assert all(int(t) in allowed | {END} for row in out for t in row)
    out_off = spec.greedy(src, lens, max_new=OUT, stop_at_end=False,
                          constraint=c, speculative=False)
    np.testing.assert_array_equal(out, out_off)


def test_constrained_outputs_satisfy_dfa(spec_pair):
    """DFA-constrained generation follows the automaton exactly: tokens
    alternate between the two edge sets, end only in accepting states,
    and nothing but end after the end (terminal parking)."""
    spec, _, spec_mm = spec_pair
    _, src, lens = _sources(seed=9)
    edges = [["a", t, "b"] for t in (2, 3)] + \
            [["b", t, "a"] for t in (8, 9)]
    dfa = {"type": "dfa", "start": "a", "edges": edges, "accept": ["a"]}
    for gen in (spec, spec_mm):      # high AND low accept rates
        out = gen.greedy(src, lens, max_new=OUT, stop_at_end=False,
                         constraint=dfa)
        for row in out:
            state = "a"
            for t in row:
                t = int(t)
                if state == "TERM":
                    assert t == END
                    continue
                if t == END:
                    assert state == "a"
                    state = "TERM"
                    continue
                assert t in ({"a": {2, 3}, "b": {8, 9}}[state])
                state = "b" if state == "a" else "a"


def test_constraint_objects_and_errors():
    """Wire-format validation + precompiled mask rows."""
    c = compile_constraint({"type": "token_set", "allowed": [3, 4]},
                           V, END)
    assert isinstance(c, TokenSetConstraint)
    row = c.mask(c.start_state())
    assert row[3] == 0.0 and row[4] == 0.0 and row[END] == 0.0
    assert row[5] == MASKED
    d = compile_constraint(
        {"type": "dfa", "start": 0, "edges": [[0, 2, 1], [1, 3, 0]],
         "accept": [0]}, V, END)
    assert isinstance(d, DFAConstraint)
    s = d.start_state()
    assert d.allows(s, 2) and not d.allows(s, 3)
    assert d.allows(s, END)              # accepting start
    s2 = d.advance(s, 2)
    assert d.allows(s2, 3) and not d.allows(s2, END)
    with pytest.raises(ValueError):
        compile_constraint({"type": "token_set"}, V, END)
    with pytest.raises(ValueError):
        compile_constraint({"type": "nope"}, V, END)
    with pytest.raises(ValueError):     # dead-end state
        compile_constraint(
            {"type": "dfa", "start": 0, "edges": [[0, 2, 1]],
             "accept": []}, V, END)
    with pytest.raises(ValueError):     # empty allowed set
        TokenSetConstraint([], V, end_id=None)
    with pytest.raises(ValueError):     # oversized edge token id
        compile_constraint(
            {"type": "dfa", "start": 0, "edges": [[0, V + 10, 0]],
             "accept": [0]}, V, END)
    with pytest.raises(ValueError):     # negative id would wrap-index
        compile_constraint(
            {"type": "dfa", "start": 0, "edges": [[0, -1, 0]],
             "accept": [0]}, V, END)


# -- scheduler integration ----------------------------------------------------

def test_scheduler_mixed_speculative_plain_integrity(spec_pair):
    """Seeded sweep: a dozen requests with interleaved speculative /
    plain / constrained decode options through 3 lanes — zero lost or
    duplicated requests, every unconstrained request token-for-token
    equal to the plain-greedy reference, allocator invariants clean."""
    spec, target, _ = spec_pair
    seqs, src, lens = _sources(seed=10, n=12)
    ref_rows = target.greedy(src, lens, max_new=OUT, stop_at_end=False)
    refs = [_trunc_at_end(r) for r in ref_rows]
    sched = ContinuousBatchingScheduler(spec, n_slots=3,
                                        max_new_tokens=OUT)
    allowed = {4, 5, 6}
    reqs = []
    for i, s in enumerate(seqs):
        decode = {"draft": i % 2 == 0}
        if i % 3 == 2:
            decode["constraint"] = {"type": "token_set",
                                    "allowed": sorted(allowed)}
        reqs.append(sched.submit(s, max_new_tokens=OUT, decode=decode))
    sched.run_until_idle()
    seen = set()
    for i, r in enumerate(reqs):
        assert r.done and r.error is None, (i, r.error)
        assert r.rid not in seen
        seen.add(r.rid)
        if i % 3 == 2:
            assert all(t in allowed | {END} for t in r.tokens), \
                (i, r.tokens)
        else:
            assert r.tokens == refs[i], (i, r.tokens, refs[i])
    st = sched.stats()
    assert st["finished"] == len(reqs) and st["failed"] == 0
    spec.check_invariants()


def test_scheduler_rejects_decode_options_on_plain_group(spec_pair):
    _, target, _ = spec_pair
    sched = ContinuousBatchingScheduler(target, n_slots=2,
                                        max_new_tokens=OUT)
    with pytest.raises(ValueError):
        sched.submit(np.arange(2, 6), max_new_tokens=4,
                     decode={"draft": True})


def test_decode_request_rerouted_to_plain_group_is_rejected(spec_pair):
    """A constrained request whose alias re-resolves to a PLAIN group
    between submit and admission (hot swap / canary fallback) must be
    REJECTED, never silently served without its grammar."""
    spec, target, _ = spec_pair
    routes = {"m": "spec"}
    sched = ContinuousBatchingScheduler(
        max_new_tokens=OUT, resolve=lambda alias: routes.get(alias,
                                                             alias))
    sched.add_model("spec", spec, 2)
    sched.add_model("plain", target, 2)
    req = sched.submit(np.arange(2, 6), max_new_tokens=4, model="m",
                       decode={"constraint": {"type": "token_set",
                                              "allowed": [4, 5]}})
    routes["m"] = "plain"       # the swap lands before admission
    sched.run_until_idle()
    assert req.done and isinstance(req.error, ValueError), req.error
    assert req.tokens == []     # nothing was served off-grammar
    # and a plain request keeps flowing through the same alias
    ok = sched.submit(np.arange(2, 6), max_new_tokens=4, model="m")
    sched.run_until_idle()
    assert ok.done and ok.error is None
    # an explicit speculation OPT-OUT ({"draft": False}, no grammar)
    # re-routed the same way is ADMITTED plain — plain decode is
    # exactly what it asked for, so rejection would be spurious
    routes["m"] = "spec"
    optout = sched.submit(np.arange(2, 6), max_new_tokens=4, model="m",
                          decode={"draft": False})
    routes["m"] = "plain"
    sched.run_until_idle()
    assert optout.done and optout.error is None
    assert optout.tokens == ok.tokens
    # the submit-time gate agrees: an opt-out submitted DIRECTLY to a
    # plain group (what journal replay does after a restart onto a
    # draftless version) is accepted, not 400d
    direct = sched.submit(np.arange(2, 6), max_new_tokens=4,
                          model="plain", decode={"draft": False})
    sched.run_until_idle()
    assert direct.done and direct.error is None
    assert direct.tokens == ok.tokens
    with pytest.raises(ValueError):     # a grammar still refuses
        sched.submit(np.arange(2, 6), max_new_tokens=4, model="plain",
                     decode={"constraint": {"type": "token_set",
                                            "allowed": [4]}})


def test_beam_speculative_mutual_exclusion(spec_pair):
    spec, _, _ = spec_pair
    with pytest.raises(NotImplementedError):
        spec.beam(np.zeros((1, SRC), np.int64),
                  np.full(1, SRC, np.int32), beam_size=2)
    with pytest.raises(ValueError):
        spec.open_slots(1)
        spec.admit_slot(0, np.arange(2, 6), max_new=4,
                        decode={"beam": 2})


# -- HBM budgeting ------------------------------------------------------------

def test_static_hbm_estimate_prices_pair(spec_pair):
    """The joint plan covers both pools and the verify-shape
    activations; components name target.* and draft.* so an
    HBMBudgetError is attributable."""
    spec, target, _ = spec_pair
    plan = spec.static_hbm_estimate(assume_lanes=4)
    t_alone = target.static_hbm_estimate(assume_lanes=4)
    assert plan.peak_bytes > t_alone.peak_bytes
    comps = plan.components
    assert any(k.startswith("target.") for k in comps)
    assert any(k.startswith("draft.") for k in comps)
    # pools are persistable state in both halves
    assert comps.get("target.kv_pool", 0) > 0
    assert comps.get("draft.kv_pool", 0) > 0


def test_scheduler_budget_refuses_oversized_pair(spec_pair):
    spec, _, _ = spec_pair
    need = spec.static_hbm_estimate(assume_lanes=2).peak_bytes
    from paddle_tpu.serving.scheduler import HBMBudgetError
    sched = ContinuousBatchingScheduler(max_new_tokens=OUT,
                                        hbm_budget_bytes=need // 2)
    with pytest.raises(HBMBudgetError):
        sched.add_model("s", spec, 2)
    sched2 = ContinuousBatchingScheduler(max_new_tokens=OUT,
                                         hbm_budget_bytes=need * 2)
    sched2.add_model("s", spec, 2)
    assert sched2.stats()["models"]["s"]["static_hbm_bytes"] == need


# -- gateway ------------------------------------------------------------------

def test_gateway_speculative_end_to_end(tmp_path, spec_pair):
    """The full request path: draft/constraint/speculate fields through
    submit, stream parity, validation failures, and a journal that
    replays decode options across a 'restart'."""
    spec, target, _ = spec_pair
    seqs, src, lens = _sources(seed=11, n=4)
    ref_rows = target.greedy(src, lens, max_new=OUT, stop_at_end=False)
    refs = [_trunc_at_end(r) for r in ref_rows]
    jpath = os.path.join(str(tmp_path), "req.jsonl")
    gw = Gateway(n_slots=3, max_new_tokens=OUT, journal_path=jpath)
    gw.load_model("m", "1", instance=spec)
    gw.serve()
    try:
        out = gw.generate("m", [int(t) for t in seqs[0]], max_new=OUT,
                          timeout=60)
        assert out["tokens"] == refs[0]
        out_plain = gw.generate("m", [int(t) for t in seqs[1]],
                                max_new=OUT, speculate=False, timeout=60)
        assert out_plain["tokens"] == refs[1]
        allowed = {4, 5, 6}
        out_c = gw.generate(
            "m", [int(t) for t in seqs[2]], max_new=OUT, timeout=60,
            constraint={"type": "token_set", "allowed": sorted(allowed)})
        assert all(t in allowed | {END} for t in out_c["tokens"])
        with gw.submit_stream("m", [int(t) for t in seqs[3]],
                              max_new=OUT) as stream:
            streamed = list(stream)
        assert streamed == refs[3]
        with pytest.raises(ValueError):
            gw.generate("m", [2, 3], draft_model="not-the-draft",
                        timeout=60)
        with pytest.raises(ValueError):     # malformed grammar: 400 path
            gw.generate("m", [2, 3], constraint={"type": "nope"},
                        timeout=60)
    finally:
        gw.shutdown(drain=True)
    assert gw.journal.pending() == []

    # plain groups refuse decode options at submit...
    gw2 = Gateway(n_slots=2, max_new_tokens=OUT)
    gw2.load_model("p", "1", instance=target)
    with pytest.raises(ValueError):
        gw2.submit("p", [2, 3], constraint={"type": "token_set",
                                            "allowed": [4]})
    with pytest.raises(ValueError):
        gw2.submit("p", [2, 3], speculate=True)
    # ...but an explicit speculate=False OPT-OUT is served plain — it
    # asks for nothing a plain group cannot do
    req = gw2.submit("p", [2, 3], speculate=False, max_new=4)
    gw2.run_until_idle()
    assert req.done and req.error is None and len(req.tokens) > 0


def test_journal_replays_decode_options(tmp_path, spec_pair):
    """A journaled constrained+speculative request survives a restart
    with its decode options intact: the recovered request decodes under
    the SAME grammar."""
    spec, _, _ = spec_pair
    seqs, _, _ = _sources(seed=12, n=1)
    jpath = os.path.join(str(tmp_path), "replay.jsonl")
    allowed = {4, 5, 6}
    c = {"type": "token_set", "allowed": sorted(allowed)}
    gw = Gateway(n_slots=2, max_new_tokens=OUT, journal_path=jpath)
    gw.load_model("m", "1", instance=spec)
    # journaled but never served: the "process died before the loop ran"
    gw.submit("m", [int(t) for t in seqs[0]], max_new=OUT, constraint=c)
    assert len(gw.journal.pending()) == 1
    assert gw.journal.pending()[0]["decode"]["constraint"] == c

    gw2 = Gateway(n_slots=2, max_new_tokens=OUT, journal_path=jpath)
    gw2.load_model("m", "1", instance=spec)
    replayed = gw2.recover()
    assert len(replayed) == 1 and replayed[0].decode["constraint"] == c
    gw2.run_until_idle()
    assert replayed[0].done and replayed[0].error is None
    assert all(t in allowed | {END} for t in replayed[0].tokens)
    assert gw2.journal.pending() == []


# -- registry artifacts + AOT -------------------------------------------------

def test_registry_load_speculative_budget_and_aot(tmp_path):
    """load_speculative: joint costing BEFORE construction (a too-small
    budget refuses with draft.* components named), and a pre-compiled
    pair loads with zero process compiles (precompile twice: second run
    all loads)."""
    from paddle_tpu.tools.aot_compile import precompile

    root = str(tmp_path)
    kw = dict(n_layer=1, n_head=2, d_key=4, d_value=4, d_model=16,
              d_inner_hid=32, max_length=64, src_len=SRC,
              max_out_len=OUT, page_size=PS, chunk_size=CHUNK,
              num_pages=32, place=fluid.CPUPlace())
    tgt = PagedTransformerGenerator(V, V, param_prefix="tg", **kw)
    tgt.init_params(seed=1)
    dr = PagedTransformerGenerator(V, V, param_prefix="dg", **kw)
    copy_weights(tgt.scope, dr.scope, prefix="tg", dst_prefix="dg")
    ModelRegistry.save_generator_artifact(tgt, root, "big", "1")
    ModelRegistry.save_generator_artifact(dr, root, "small", "1")

    from paddle_tpu.serving.scheduler import HBMBudgetError
    reg_small = ModelRegistry(root=root, hbm_budget_bytes=1024,
                              place=fluid.CPUPlace())
    with pytest.raises(HBMBudgetError) as ei:
        reg_small.load_speculative("big", "1", "small", "1", k=2)
    assert "draft." in str(ei.value)

    first = precompile(os.path.join(root, "big", "1"), n_slots=2,
                       draft_dirname=os.path.join(root, "small", "1"),
                       speculate_k=2)
    assert first["kind"] == "speculative" and first["compiles"] == 3
    second = precompile(os.path.join(root, "big", "1"), n_slots=2,
                        draft_dirname=os.path.join(root, "small", "1"),
                        speculate_k=2)
    assert second["compiles"] == 0 and second["loads"] == 3
    assert sorted(second["keys"]) == sorted(first["keys"])

    # a fresh registry load of the pre-compiled pair serves its first
    # tokens with zero process compiles
    reg = ModelRegistry(root=root, place=fluid.CPUPlace())
    key = reg.load_speculative("big", "1", "small", "1", k=2)
    inst = reg.instance(key)
    assert reg.entries()[0]["kind"] == "speculative"
    inst.aot_warm(2)
    # decode at the warmed lane count: batch == n_slots == 2, so the
    # dispatch signatures match what precompile shipped
    out = inst.greedy(np.asarray([[3, 4, 5, 6], [6, 5, 4, 3]], np.int64),
                      np.asarray([4, 4], np.int32), max_new=4,
                      stop_at_end=False)
    assert out.shape == (2, 4)
    for exe_half in (inst.target.exe, inst.draft.exe):
        assert exe_half.cache_stats()["persistent"]["misses"] == 0

    # an in-flight load of the same key makes a concurrent duplicate
    # fail FAST (reservation) instead of double-building the pair on
    # device and silently overwriting the first entry
    reg2 = ModelRegistry(root=root, place=fluid.CPUPlace())
    reg2._loading.add("big@1")
    with pytest.raises(ValueError, match="already loaded"):
        reg2.load("big", "1")
    with pytest.raises(ValueError, match="already loaded"):
        reg2.load_speculative("big", "1", "small", "1", k=2)
    reg2._loading.clear()
    # a FAILED load releases its reservation (the finally path)
    with pytest.raises(FileNotFoundError):
        reg2.load("big", "9")
    assert "big@9" not in reg2._loading
    reg2.load("big", "1")           # reservation gone: loads fine


def test_constraint_cache_byte_budget(spec_pair):
    """The compiled-constraint memo evicts by resident mask BYTES, not
    just entry count — a few huge grammars must not pin unbounded host
    memory — while the just-inserted entry always stays resident."""
    spec, _, _ = spec_pair
    spec._constraint_cache.clear()
    spec._constraint_bytes = 0
    row = V * 4                       # one float32 [vocab] mask row
    spec._CONSTRAINT_CACHE_MAX_BYTES = 2 * row   # instance shadow
    try:
        spec.compile_constraint({"type": "token_set", "allowed": [3]})
        spec.compile_constraint({"type": "token_set", "allowed": [4]})
        assert len(spec._constraint_cache) == 2
        spec.compile_constraint({"type": "token_set", "allowed": [5]})
        assert len(spec._constraint_cache) == 2      # oldest evicted
        assert spec._constraint_bytes <= 2 * row
        # an entry that alone exceeds the budget still serves its
        # bringing request: resident as the single cache entry
        spec._CONSTRAINT_CACHE_MAX_BYTES = row // 2
        spec.compile_constraint({"type": "token_set", "allowed": [6]})
        assert len(spec._constraint_cache) == 1
    finally:
        del spec._CONSTRAINT_CACHE_MAX_BYTES
        spec._constraint_cache.clear()
        spec._constraint_bytes = 0


def test_constraint_cache_thread_safety(spec_pair):
    """Gateway HTTP threads validate constraints concurrently with the
    serve loop's admissions: hammered from four threads, the memo never
    raises (the unlocked LRU's pop-after-evict KeyError) and the byte
    accounting matches the resident entries exactly (no double-count
    from same-spec compile races)."""
    import threading

    spec, _, _ = spec_pair
    spec._constraint_cache.clear()
    spec._constraint_bytes = 0
    spec._CONSTRAINT_CACHE_MAX_BYTES = 4 * V * 4   # churn: ~4 entries
    errs = []

    def worker(i):
        try:
            for j in range(60):
                spec.compile_constraint(
                    {"type": "token_set",
                     "allowed": [2 + (i + j) % 10]})
        except Exception as e:          # pragma: no cover - the bug
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(4)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not errs, errs
        assert spec._constraint_bytes == sum(
            c.mask_bytes() for c in spec._constraint_cache.values())
    finally:
        del spec._CONSTRAINT_CACHE_MAX_BYTES
        spec._constraint_cache.clear()
        spec._constraint_bytes = 0


def test_http_speculative_fields_and_load_validation(spec_pair):
    """The HTTP front end: /v1/generate carries constraint/speculate/
    draft_model (wrong draft name 400s), and /v1/models load refuses
    stray draft fields without draft_model instead of silently loading
    a plain group."""
    import json
    import urllib.error
    import urllib.request

    from paddle_tpu.serving.gateway import GatewayServer

    def post(addr, route, body):
        req = urllib.request.Request(
            f"http://{addr}{route}", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        return urllib.request.urlopen(req, timeout=60)

    spec, target, _ = spec_pair
    seqs, src, lens = _sources(seed=21, n=1)
    ref = _trunc_at_end(target.greedy(src, lens, max_new=OUT,
                                      stop_at_end=False)[0])
    gw = Gateway(n_slots=2, max_new_tokens=OUT)
    gw.load_model("m", "1", instance=spec)
    srv = GatewayServer(gw)
    addr = srv.start()
    try:
        prompt = [int(t) for t in seqs[0]]
        out = json.loads(post(addr, "/v1/generate",
                              {"model": "m", "prompt": prompt,
                               "max_new": OUT}).read())
        assert out["tokens"] == ref
        allowed = {4, 5, 6}
        out_c = json.loads(post(
            addr, "/v1/generate",
            {"model": "m", "prompt": prompt, "max_new": OUT,
             "constraint": {"type": "token_set",
                            "allowed": sorted(allowed)}}).read())
        assert all(t in allowed | {END} for t in out_c["tokens"])
        with pytest.raises(urllib.error.HTTPError) as e:
            post(addr, "/v1/generate",
                 {"model": "m", "prompt": prompt,
                  "draft_model": "not-the-draft"})
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            post(addr, "/v1/models",
                 {"action": "load", "model": "x", "version": "1",
                  "draft_version": "1", "speculate_k": 2})
        assert e.value.code == 400
        assert "draft_model" in json.loads(
            e.value.read().decode())["error"]
        with pytest.raises(urllib.error.HTTPError) as e:   # swap too
            post(addr, "/v1/models",
                 {"action": "swap", "model": "x", "version": "1",
                  "speculate_k": 2})
        assert e.value.code == 400
    finally:
        srv.stop()
        gw.shutdown(drain=True)


def test_verify_program_cost_plan_clean(spec_pair):
    """The k-token verify program goes through the static cost analyzer
    without unregistered-cost-rule findings, and its plan charges the
    pool plus the K-wide activations/mask feed."""
    spec, _, _ = spec_pair
    from paddle_tpu.fluid.analysis.cost import plan_program

    prog = spec._verify[0]
    diags = prog.analyze(level="cost")
    assert not [f for f in diags.findings
                if f.code == "cost/unregistered-cost-rule"], \
        [str(f) for f in diags.findings]
    plan = plan_program(prog, assume_batch=4)
    assert plan.components.get("kv_pool", 0) > 0
    # the [lanes, K, vocab] mask is a real feed the plan must price
    plan1 = plan_program(spec._draft_prog[0], assume_batch=4)
    assert plan.peak_bytes > 0 and plan1.peak_bytes > 0
