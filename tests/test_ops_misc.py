"""OpTests for the r2 operator batch (VERDICT missing#7): pad, crop,
lod_reset, lrn, label_smooth, rank/margin-rank/log/modified-huber
losses, conv_shift, row_conv, lstmp, max_pool2d_with_index, unpool,
roi_pool, spp, prior_box, bipartite_match, multiclass_nms.

Numpy goldens + finite-difference grad checks for the differentiable
ones — the reference's OpTest contract.
"""

import numpy as np
import pytest

from paddle_tpu import fluid
from paddle_tpu.fluid import SeqArray, make_seq
from tests.op_test import OpTestCase


def _r(*shape, seed=0):
    return np.random.RandomState(seed).rand(*shape).astype(np.float32)


class TestPadCrop:
    def test_pad(self):
        x = _r(2, 3)
        t = OpTestCase("pad", {"X": x},
                       {"paddings": [1, 0, 0, 2], "pad_value": 0.5})
        t.check_output({"Out": np.pad(x, ((1, 0), (0, 2)),
                                      constant_values=0.5)})
        t.check_grad(["X"])

    def test_crop_attr_shape(self):
        x = _r(4, 5)
        t = OpTestCase("crop", {"X": x},
                       {"offsets": [1, 2], "shape": [2, 3]})
        t.check_output({"Out": x[1:3, 2:5]})
        t.check_grad(["X"])

    def test_crop_from_y(self):
        x, y = _r(4, 5), np.zeros((2, 2), np.float32)
        t = OpTestCase("crop", {"X": x, "Y": y}, {"offsets": [0, 1]})
        t.check_output({"Out": x[0:2, 1:3]})

    def test_lod_reset(self):
        seq = make_seq([[1, 2, 3], [4, 5]], dtype=np.float32, bucket=3)
        t = OpTestCase("lod_reset", {"X": seq},
                       {"target_lod": [0, 1, 4]})
        out = t.run_single()
        assert isinstance(out, SeqArray)
        np.testing.assert_array_equal(np.asarray(out.lengths), [1, 3])
        np.testing.assert_array_equal(np.asarray(out.data),
                                      np.asarray(seq.data))


class TestNormalizeAndLosses:
    def test_lrn(self):
        x = _r(2, 7, 3, 3)
        n, k, alpha, beta = 5, 2.0, 1e-4, 0.75
        sq = x * x
        pad = np.pad(sq, ((0, 0), (n // 2, n // 2), (0, 0), (0, 0)))
        acc = sum(pad[:, i: i + 7] for i in range(n))
        want = x / (k + alpha * acc) ** beta
        t = OpTestCase("lrn", {"X": x},
                       {"n": n, "k": k, "alpha": alpha, "beta": beta})
        t.check_output({"Out": want, "MidOut": k + alpha * acc})
        t.check_grad(["X"], output_slots=["Out"])

    def test_label_smooth(self):
        x = np.eye(4, dtype=np.float32)
        t = OpTestCase("label_smooth", {"X": x}, {"epsilon": 0.1})
        t.check_output({"Out": 0.9 * x + 0.1 / 4})

    def test_label_smooth_prior(self):
        x = np.eye(4, dtype=np.float32)
        prior = np.full((4,), 0.25, np.float32)
        t = OpTestCase("label_smooth", {"X": x, "PriorDist": prior},
                       {"epsilon": 0.2})
        t.check_output({"Out": 0.8 * x + 0.2 * prior})

    def test_rank_loss(self):
        lbl, lt, rt = _r(6, 1, seed=1), _r(6, 1, seed=2), _r(6, 1, seed=3)
        c = lt - rt
        want = np.log1p(np.exp(c)) - lbl * c
        t = OpTestCase("rank_loss",
                       {"Label": lbl, "Left": lt, "Right": rt})
        t.check_output({"Out": want})
        t.check_grad(["Left", "Right"])

    def test_margin_rank_loss(self):
        lbl = np.sign(_r(6, 1, seed=4) - 0.5).astype(np.float32)
        x1, x2 = _r(6, 1, seed=5), _r(6, 1, seed=6)
        raw = -lbl * (x1 - x2) + 0.1
        t = OpTestCase("margin_rank_loss",
                       {"Label": lbl, "X1": x1, "X2": x2},
                       {"margin": 0.1})
        t.check_output({"Out": np.maximum(raw, 0),
                        "Activated": (raw > 0).astype(np.float32)})
        t.check_grad(["X1", "X2"], output_slots=["Out"])

    def test_log_loss(self):
        p = np.clip(_r(8, 1, seed=7), 0.05, 0.95)
        y = (_r(8, 1, seed=8) > 0.5).astype(np.float32)
        eps = 1e-4
        want = -y * np.log(p + eps) - (1 - y) * np.log(1 - p + eps)
        t = OpTestCase("log_loss", {"Predicted": p, "Labels": y},
                       {"epsilon": eps})
        t.check_output({"Loss": want})
        t.check_grad(["Predicted"], output_slots=["Loss"])

    def test_modified_huber_loss(self):
        x = (_r(10, 1, seed=9) * 4 - 2).astype(np.float32)
        y = (_r(10, 1, seed=10) > 0.5).astype(np.float32)
        v = (2 * y - 1) * x
        want = np.where(v < -1, -4 * v, np.maximum(0, 1 - v) ** 2)
        t = OpTestCase("modified_huber_loss", {"X": x, "Y": y})
        t.check_output({"Out": want.astype(np.float32),
                        "IntermediateVal": v})


class TestSequenceKernels:
    def test_conv_shift(self):
        x, y = _r(3, 8, seed=11), _r(3, 3, seed=12)
        w, m = 8, 3
        want = np.zeros_like(x)
        for b in range(3):
            for i in range(w):
                for j in range(m):
                    want[b, i] += x[b, (i + j - m // 2) % w] * y[b, j]
        t = OpTestCase("conv_shift", {"X": x, "Y": y})
        t.check_output({"Out": want})
        t.check_grad(["X", "Y"])

    def test_row_conv(self):
        lens = [4, 2]
        seq = SeqArray(_r(2, 4, 3, seed=13), np.array(lens))
        w = _r(2, 3, seed=14)          # future context 1
        want = np.zeros((2, 4, 3), np.float32)
        for b, L in enumerate(lens):
            for t_ in range(L):
                for j in range(2):
                    if t_ + j < L:
                        want[b, t_] += seq.data[b, t_ + j] * w[j]
        t = OpTestCase("row_conv", {"X": seq, "Filter": w})
        out = t.run_single()
        np.testing.assert_allclose(np.asarray(out.data), want, atol=1e-5)
        t.check_grad(["X", "Filter"])

    def test_lstmp_shapes_and_projection(self):
        size, proj = 6, 4
        seq = SeqArray(_r(2, 5, 4 * size, seed=15), np.array([5, 3]))
        w = _r(proj, 4 * size, seed=16) * 0.1
        w_proj = _r(size, proj, seed=17) * 0.1
        b = _r(1, 7 * size, seed=18) * 0.1
        t = OpTestCase("lstmp",
                       {"Input": seq, "Weight": w, "ProjWeight": w_proj,
                        "Bias": b})
        outs = t.run_all()
        r, c = outs["Projection"][0], outs["Cell"][0]
        assert r.data.shape == (2, 5, proj)
        assert c.data.shape == (2, 5, size)
        assert np.isfinite(np.asarray(r.data)).all()
        # projection really feeds back: zeroing ProjWeight changes output
        outs0 = OpTestCase("lstmp",
                           {"Input": seq, "Weight": w,
                            "ProjWeight": np.zeros_like(w_proj),
                            "Bias": b}).run_all()
        assert not np.allclose(np.asarray(r.data),
                               np.asarray(outs0["Projection"][0].data))
        t.check_grad(["Input", "Weight", "ProjWeight"],
                     output_slots=["Projection"])


class TestSpatialPooling:
    def test_max_pool_with_index_and_unpool(self):
        x = _r(1, 2, 4, 4, seed=19)
        t = OpTestCase("max_pool2d_with_index", {"X": x},
                       {"ksize": [2, 2], "strides": [2, 2]})
        outs = t.run_all()
        out, mask = outs["Out"][0], outs["Mask"][0]
        assert out.shape == (1, 2, 2, 2)
        # golden max pool
        want = x.reshape(1, 2, 2, 2, 2, 2).max(axis=(3, 5))
        np.testing.assert_allclose(np.asarray(out), want, atol=1e-6)
        # unpool scatters back to the argmax positions
        t2 = OpTestCase("unpool",
                        {"X": np.asarray(out), "Indices": np.asarray(mask)},
                        {"unpooled_size": [4, 4]})
        rec = t2.run_single()
        assert rec.shape == (1, 2, 4, 4)
        # every pooled value present at its recorded position
        flat = np.asarray(rec).reshape(2, 16)
        for ci in range(2):
            for v, i in zip(np.asarray(out)[0, ci].ravel(),
                            np.asarray(mask)[0, ci].ravel()):
                assert flat[ci, i] == v

    def test_roi_pool(self):
        x = np.arange(32, dtype=np.float32).reshape(1, 2, 4, 4)
        rois = np.array([[0, 0, 0, 1, 1],      # top-left 2x2
                         [0, 2, 2, 3, 3]], np.float32)  # bottom-right
        t = OpTestCase("roi_pool", {"X": x, "ROIs": rois},
                       {"pooled_height": 1, "pooled_width": 1,
                        "spatial_scale": 1.0})
        out = t.run_single()
        assert out.shape == (2, 2, 1, 1)
        np.testing.assert_allclose(np.asarray(out)[0, 0, 0, 0],
                                   x[0, 0, :2, :2].max())
        np.testing.assert_allclose(np.asarray(out)[1, 0, 0, 0],
                                   x[0, 0, 2:, 2:].max())

    def test_spp(self):
        x = _r(2, 3, 4, 4, seed=20)
        t = OpTestCase("spp", {"X": x}, {"pyramid_height": 2})
        out = t.run_single()
        # levels: 1x1 + 2x2 bins -> c*(1+4) features
        assert out.shape == (2, 3 * 5)
        np.testing.assert_allclose(np.asarray(out)[:, :3],
                                   x.max(axis=(2, 3)), atol=1e-6)


class TestDetection:
    def test_prior_box(self):
        feat = np.zeros((1, 8, 2, 2), np.float32)
        img = np.zeros((1, 3, 32, 32), np.float32)
        t = OpTestCase("prior_box", {"Input": feat, "Image": img},
                       {"min_sizes": [8.0], "max_sizes": [16.0],
                        "aspect_ratios": [2.0], "flip": True,
                        "clip": True})
        outs = t.run_all()
        boxes, var = outs["Boxes"][0], outs["Variances"][0]
        # priors: ar 1 + ar 2 + ar 0.5 + max-size extra = 4
        assert boxes.shape == (2, 2, 4, 4)
        assert var.shape == boxes.shape
        b = np.asarray(boxes)
        assert (b >= 0).all() and (b <= 1).all()        # clipped
        # first prior of cell (0,0): square min_size box at center (8,8)
        np.testing.assert_allclose(
            b[0, 0, 0], [(8 - 4) / 32, (8 - 4) / 32,
                         (8 + 4) / 32, (8 + 4) / 32], atol=1e-6)

    def test_bipartite_match_greedy(self):
        dist = np.array([[0.9, 0.1, 0.2],
                         [0.8, 0.7, 0.3]], np.float32)
        t = OpTestCase("bipartite_match", {"DistMat": dist})
        outs = t.run_all()
        idx = np.asarray(outs["ColToRowMatchIndices"][0])
        d = np.asarray(outs["ColToRowMatchDist"][0])
        # greedy: (0,0)=0.9 first, then (1,1)=0.7; col 2 unmatched
        np.testing.assert_array_equal(idx, [0, 1, -1])
        np.testing.assert_allclose(d, [0.9, 0.7, 0.0], atol=1e-6)

    def test_bipartite_match_per_prediction(self):
        dist = np.array([[0.9, 0.1, 0.6],
                         [0.8, 0.7, 0.3]], np.float32)
        t = OpTestCase("bipartite_match", {"DistMat": dist},
                       {"match_type": "per_prediction",
                        "dist_threshold": 0.5})
        outs = t.run_all()
        idx = np.asarray(outs["ColToRowMatchIndices"][0])
        # col 2 tops up with its argmax row 0 (0.6 >= 0.5)
        np.testing.assert_array_equal(idx, [0, 1, 0])

    def test_multiclass_nms(self):
        boxes = np.array([[0, 0, 1, 1],
                          [0, 0, 0.95, 0.95],     # heavy overlap with 0
                          [2, 2, 3, 3]], np.float32)
        scores = np.array([[0.9, 0.8, 0.7]], np.float32)   # one class
        t = OpTestCase("multiclass_nms", {"BBoxes": boxes,
                                          "Scores": scores},
                       {"nms_threshold": 0.5, "keep_top_k": 3,
                        "score_threshold": 0.05})
        out = np.asarray(t.run_single())
        kept = out[out[:, 0] >= 0]
        # box 1 suppressed by box 0; boxes 0 and 2 kept
        assert len(kept) == 2
        np.testing.assert_allclose(sorted(kept[:, 1].tolist()),
                                   [0.7, 0.9], atol=1e-6)


def test_unpool_layer_roundtrip():
    """r2 review: unpool must be reachable through the layer API —
    max_pool2d_with_index layer produces its Indices input."""
    from paddle_tpu import fluid

    x = _r(1, 2, 4, 4, seed=21)
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        xin = fluid.layers.data("x", [2, 4, 4], "float32")
        pooled, mask = fluid.layers.max_pool2d_with_index(xin, 2)
        restored = fluid.layers.unpool(pooled, mask, [4, 4])
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        p, r = exe.run(main, feed={"x": x}, fetch_list=[pooled, restored])
    want = x.reshape(1, 2, 2, 2, 2, 2).max(axis=(3, 5))
    np.testing.assert_allclose(np.asarray(p), want, atol=1e-6)
    assert np.asarray(r).shape == (1, 2, 4, 4)
    # restored contains exactly the pooled values at argmax positions
    np.testing.assert_allclose(np.sort(np.asarray(r)[np.asarray(r) != 0]),
                               np.sort(want.ravel()), atol=1e-6)


def test_spp_tiny_map_no_inf():
    """r2 review: pyramid levels deeper than the feature map must not
    emit -inf features."""
    t = OpTestCase("spp", {"X": _r(1, 2, 2, 2, seed=22)},
                   {"pyramid_height": 3})
    out = np.asarray(t.run_single())
    assert np.isfinite(out).all()


def test_hsigmoid_matches_bitcode_reference(fresh_programs):
    """hsigmoid vs a per-sample numpy walk of the reference SimpleCode
    tree (math/MatrixBitCode.cpp: c = label + C, index=(c>>(j+1))-1,
    bit=(c>>j)&1, len=floor(log2 c))."""
    main, startup, scope = fresh_programs
    x = fluid.layers.data("x", [6], "float32")
    lbl = fluid.layers.data("lbl", [1], "int64")
    cost = fluid.layers.hsigmoid(x, lbl, num_classes=5,
                                 param_attr=fluid.ParamAttr(name="hs_w"),
                                 bias_attr=fluid.ParamAttr(name="hs_b"))
    loss = fluid.layers.mean(cost)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    xs = rng.randn(4, 6).astype(np.float32)
    ls = np.array([[0], [1], [3], [4]], np.int64)
    with fluid.scope_guard(scope):
        exe.run(startup)
        W = np.asarray(scope.find_var("hs_w"))
        B = np.asarray(scope.find_var("hs_b"))
        c0, = exe.run(main, feed={"x": xs, "lbl": ls}, fetch_list=[cost])

        def naive(xi, li):
            c = li + 5
            out = 0.0
            for j in range(int(np.floor(np.log2(c)))):
                idx = (c >> (j + 1)) - 1
                bit = (c >> j) & 1
                pre = np.clip(W[idx] @ xi + B[idx], -40, 40)
                out += np.log1p(np.exp(pre)) - bit * pre
            return out

        want = np.array([[naive(xs[i], int(ls[i, 0]))] for i in range(4)])
        np.testing.assert_allclose(np.asarray(c0), want, rtol=1e-5,
                                   atol=1e-6)
        # trains: loss decreases on a fixed batch
        vals = [float(np.asarray(exe.run(main, feed={"x": xs, "lbl": ls},
                                         fetch_list=[loss])[0]))
                for _ in range(25)]
        assert vals[-1] < vals[0]


def test_bilinear_interp_align_corners(fresh_programs):
    main, startup, scope = fresh_programs
    img = fluid.layers.data("img", [1, 2, 3], "float32")
    up = fluid.layers.bilinear_interp(img, out_h=4, out_w=6)
    g = fluid.layers.mean(up)
    exe = fluid.Executor(fluid.CPUPlace())
    im = np.random.RandomState(1).rand(2, 1, 2, 3).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        u, = exe.run(main, feed={"img": im}, fetch_list=[up])
    u = np.asarray(u)
    assert u.shape == (2, 1, 4, 6)
    # align-corners mapping keeps the four corners exactly
    np.testing.assert_allclose(u[:, :, 0, 0], im[:, :, 0, 0], rtol=1e-6)
    np.testing.assert_allclose(u[:, :, 0, -1], im[:, :, 0, -1], rtol=1e-6)
    np.testing.assert_allclose(u[:, :, -1, 0], im[:, :, -1, 0], rtol=1e-6)
    np.testing.assert_allclose(u[:, :, -1, -1], im[:, :, -1, -1],
                               rtol=1e-6)
    # interior row 1 (y = 1/3 between the input rows) at column 0
    want = im[:, :, 0, 0] + (im[:, :, 1, 0] - im[:, :, 0, 0]) / 3.0
    np.testing.assert_allclose(u[:, :, 1, 0], want, rtol=1e-5)


def test_sampling_id_distribution(fresh_programs):
    main, startup, scope = fresh_programs
    probs = fluid.layers.data("probs", [4], "float32")
    sid = fluid.layers.sampling_id(probs)
    exe = fluid.Executor(fluid.CPUPlace())
    pr = np.tile(np.array([[0.05, 0.05, 0.8, 0.1]], np.float32), (256, 1))
    with fluid.scope_guard(scope):
        exe.run(startup)
        s1, = exe.run(main, feed={"probs": pr}, fetch_list=[sid])
    s1 = np.asarray(s1)
    assert s1.shape == (256, 1)
    assert set(np.unique(s1)) <= {0, 1, 2, 3}
    frac = (s1.ravel() == 2).mean()
    assert 0.6 < frac < 0.95, frac


class TestIsfinite:
    """reference isfinite_op.cc — the nan/inf check `layers.isfinite`
    exposes and the guardrail sentinel fuses into the training step
    (COMPAT.md "Training guardrails")."""

    def test_all_finite_true(self):
        t = OpTestCase("isfinite", {"X": [_r(3, 4), _r(2, 2, seed=1)]})
        t.check_output({"Out": np.array(True)})

    def test_nan_detected(self):
        x = _r(3, 4)
        x[1, 2] = np.nan
        t = OpTestCase("isfinite", {"X": [x]})
        t.check_output({"Out": np.array(False)})

    def test_inf_detected_across_inputs(self):
        clean, dirty = _r(3, 4), _r(2, 2, seed=1)
        dirty[0, 0] = -np.inf
        t = OpTestCase("isfinite", {"X": [clean, dirty]})
        t.check_output({"Out": np.array(False)})

    def test_int_inputs_vacuously_finite(self):
        t = OpTestCase("isfinite",
                       {"X": [np.arange(6, dtype=np.int32).reshape(2, 3)]})
        t.check_output({"Out": np.array(True)})

    def test_scalar_bool_shape(self):
        out = OpTestCase("isfinite", {"X": [_r(3, 4)]}).run_single()
        arr = np.asarray(out)
        assert arr.shape == () and arr.dtype == np.bool_
