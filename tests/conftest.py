"""Test harness config: force an 8-device virtual CPU mesh BEFORE jax import.

This is how we test multi-chip sharding without TPU pods — the improvement
SURVEY.md §4 calls for over the reference (whose distributed tests were
excluded from CI as `notest_*`)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env may point at TPU
# zero-egress CI: datasets serve their synthetic stand-ins instead of
# stalling on download timeouts (test_datasets.py covers the real parse
# paths via local fixtures and clears this when exercising fallbacks)
os.environ.setdefault("PADDLE_TPU_SYNTHETIC", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# the axon sitecustomize may have pinned jax_platforms to the TPU tunnel
# before this conftest ran; override at the config level too.
import jax

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, jax.devices()

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-process chaos/restart tests excluded from the "
        "tier-1 `-m 'not slow'` run")


@pytest.fixture
def fresh_programs():
    """Give a test its own main/startup programs and scope (the reference's
    tests do the same via new Program() + program_guard)."""
    from paddle_tpu import fluid

    main = fluid.Program()
    startup = fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            fluid.unique_name.guard():
        yield main, startup, scope


def rng(seed=0):
    return np.random.RandomState(seed)
