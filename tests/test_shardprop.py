"""shardprop (ISSUE 18): whole-program SPMD sharding inference.

Three bars, mirroring test_analysis.py's structure:

* **seeded defects** — one hand-built fixture per finding code
  (shard/resharding-hazard, shard/partial-sum-unreduced,
  shard/dp-grad-divergence, shard/replicated-giant,
  shard/unregistered-prop-rule), each detected with exact
  block/op#/slot coordinates;
* **differential gate** — the inferred collective graph must match
  ``Executor.collective_analysis`` (compiled-HLO ground truth)
  op-for-op: equal counts AND equal payload bytes per collective kind
  (rel_err 0.0), on 2- and 4-device virtual meshes, for the sharded
  unified decode step, the sharded speculative verify program, and a
  dp-sharded training program;
* **zero errors on real programs** — book-style nets, the transpiler's
  emitted programs, and the registry's manifest-built generators all
  propagate clean.
"""

import json

import numpy as np
import pytest

from paddle_tpu import fluid
from paddle_tpu.fluid.analysis import (LEVELS, ProgramValidationError,
                                       analyze_program)
from paddle_tpu.fluid.analysis.comms import WIRE_RULES, estimate_comms
from paddle_tpu.fluid.analysis.cost import COST_RULES
from paddle_tpu.fluid.analysis.shardprop import (PROP_RULES,
                                                 PROPAGATION_OPAQUE,
                                                 compare_collectives,
                                                 has_prop_rule,
                                                 infer_sharding)
from paddle_tpu.fluid.core.desc import OpDesc, VarDesc
from paddle_tpu.parallel import mesh as pmesh
from paddle_tpu.parallel.transpiler import DistributeTranspiler

KW = dict(src_vocab_size=37, trg_vocab_size=37, n_layer=2, n_head=4,
          d_key=8, d_value=8, d_model=32, d_inner_hid=64, max_length=64,
          src_len=16, max_out_len=10, page_size=4, chunk_size=4)


def _train_net():
    """fc -> fc -> cross_entropy -> mean, SGD-minimized."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[64], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=128, act="relu")
        pred = fluid.layers.fc(input=h, size=10, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=y))
        opt_ops, pg = fluid.optimizer.SGD(learning_rate=0.01).minimize(
            loss)
    return main, startup, loss, opt_ops, pg


# ---------------------------------------------------------------------------
# wire-byte rules + per-kind subtotals (satellite: comms.py)
# ---------------------------------------------------------------------------

def test_wire_rules_golden():
    # ring all-reduce moves each byte out and back in: 2(n-1)/n
    assert WIRE_RULES["all-reduce"](1000.0, 4) == 1500.0
    assert WIRE_RULES["all-reduce"](1000.0, 2) == 1000.0
    # one-direction shuffles: (n-1)/n of the payload crosses the wire
    for kind in ("all-gather", "reduce-scatter", "all-to-all"):
        assert WIRE_RULES[kind](1000.0, 4) == 750.0, kind
        assert WIRE_RULES[kind](1000.0, 2) == 500.0, kind
    # an unknown/degenerate extent clamps to the assume-2 fallback the
    # whole estimator uses (shardprop never records extent-1 axes, so
    # the clamp is only ever the unknown-axis default)
    assert WIRE_RULES["all-reduce"](1000.0, 1) == 1000.0
    assert WIRE_RULES["all-gather"](1000.0, 1) == 500.0


def test_comms_report_per_kind_subtotals():
    """estimate_comms prices an inferred collective graph entry-for-entry
    and reports per-hlo-kind subtotals in to_dict()."""
    graph = [
        {"axis": "mp", "hlo_kind": "all-reduce", "payload_bytes": 100.0,
         "at": "block 0 op#1 (mul)", "grad": False},
        {"axis": "mp", "hlo_kind": "all-reduce", "payload_bytes": 100.0,
         "at": "block 0 op#5 (mul)", "grad": False},
        {"axis": "dp", "hlo_kind": "all-reduce", "payload_bytes": 40.0,
         "at": "block 0 op#9 (mul_grad)", "grad": True},
        {"axis": "mp", "hlo_kind": "all-gather", "payload_bytes": 64.0,
         "at": "block 0 op#3 (concat)", "grad": False},
    ]
    prog = fluid.Program()
    rep = estimate_comms(prog, options={
        "mesh_axes": {"mp": 2, "dp": 4}, "collectives": graph})
    d = rep.to_dict()
    assert d["per_kind"]["all-reduce"]["count"] == 3
    assert d["per_kind"]["all-reduce"]["payload_bytes"] == 240.0
    # 2*(100 @ mp=2 -> 100) + (40 @ dp=4 -> 60)
    assert d["per_kind"]["all-reduce"]["wire_bytes"] == 260.0
    assert d["per_kind"]["all-gather"] == {
        "count": 1, "payload_bytes": 64.0, "wire_bytes": 32.0}
    assert rep.grad_sync_bytes == 40.0
    assert len(rep.collectives) == 4


# ---------------------------------------------------------------------------
# seeded defects: exact coordinates per finding code
# ---------------------------------------------------------------------------

def test_resharding_hazard_exact_coordinates():
    """Same dim of an elementwise op's operands sharded over two
    different mesh axes: a forced repartition, priced as an
    all-gather."""
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()), \
            fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[8, 8], dtype="float32")
        y = fluid.layers.data(name="y", shape=[8, 8], dtype="float32")
        out = fluid.layers.elementwise_add(x, y)
    b = main.global_block().desc
    b.vars["x"].sharding = [None, "mp", None]
    b.vars["y"].sharding = [None, "np", None]
    res = infer_sharding(main, options={"mesh_axes": {"mp": 2, "np": 2}},
                         fetch=[out.name])
    found = [f for f in res.findings if f.code == "resharding-hazard"]
    assert len(found) == 1, [f.render() for f in res.findings]
    f = found[0]
    assert f.severity == "error"
    assert (f.block, f.op, f.op_type) == (0, 0, "elementwise_add")
    assert f.slot == "Y#0" and f.var == "y"
    # ...and the repartition is on the collective bill
    gathers = [c for c in res.collectives
               if c["hlo_kind"] == "all-gather"]
    assert len(gathers) == 1 and gathers[0]["op"] == 0


def test_partial_sum_unreduced_exact_coordinates():
    """A row-parallel matmul's output is a partial sum over the model
    axis; fetching it without the all-reduce means every shard returns
    a different value."""
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()), \
            fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        h = fluid.layers.fc(input=x, size=4, bias_attr=False)
    b = main.global_block().desc
    w = [n for n in b.vars if n.endswith(".w_0")][0]
    b.vars[w].sharding = ["mp", None]          # contracted dim sharded
    res = infer_sharding(main, options={"mesh_axes": {"mp": 2}},
                         fetch=[h.name])
    found = [f for f in res.findings
             if f.code == "partial-sum-unreduced"]
    assert len(found) == 1, [f.render() for f in res.findings]
    f = found[0]
    assert f.severity == "error"
    assert (f.block, f.op, f.op_type) == (0, 0, "mul")
    assert f.var == h.name
    # un-fetched, the partial is legal: its all-reduce gets priced
    res2 = infer_sharding(main, options={"mesh_axes": {"mp": 2}})
    assert not [f for f in res2.findings if f.severity == "error"]
    assert [c["hlo_kind"] for c in res2.collectives] == ["all-reduce"]
    assert res2.collectives[0]["op"] == 0


def test_dp_grad_divergence_exact_coordinates():
    """A gradient declared to stay dp-sharded reaches the optimizer:
    each replica would apply a different update."""
    main, _, loss, opt_ops, pg = _train_net()
    b = main.global_block().desc
    p = pg[0][0].name
    b.vars[p + "@GRAD"].sharding = ["dp", None]
    res = infer_sharding(main, options={"mesh_axes": {"dp": 2},
                                        "assume_batch": 8},
                         fetch=[loss.name])
    found = [f for f in res.findings if f.code == "dp-grad-divergence"]
    assert len(found) == 1, [f.render() for f in res.findings]
    f = found[0]
    sgd = [i for i, op in enumerate(b.ops)
           if op.type == "sgd" and op.inputs.get("Param") == [p]]
    assert (f.block, f.op, f.op_type) == (0, sgd[0], "sgd")
    assert f.severity == "error" and f.var == p and f.slot == "Grad#0"


def test_replicated_giant_threshold_and_coordinates():
    main, _, loss, _, _ = _train_net()
    res = infer_sharding(main, options={"mesh_axes": {"model": 2},
                                        "replicated_giant_bytes": 10_000},
                         fetch=[loss.name])
    found = [f for f in res.findings if f.code == "replicated-giant"]
    # only fc_0's [64,128] fp32 weight (32 KiB) crosses the threshold
    assert len(found) == 1, [f.render() for f in found]
    f = found[0]
    assert f.severity == "error" and f.block == 0
    assert f.var.endswith(".w_0") and "MiB" in f.message
    # sharding that weight on the model axis clears it
    b = main.global_block().desc
    b.vars[f.var].sharding = [None, "model"]
    res2 = infer_sharding(main, options={
        "mesh_axes": {"model": 2}, "replicated_giant_bytes": 10_000},
        fetch=[loss.name])
    assert not [x for x in res2.findings
                if x.code == "replicated-giant"]
    # threshold None disables the check entirely
    res3 = infer_sharding(main, options={
        "mesh_axes": {"model": 2}, "replicated_giant_bytes": None},
        fetch=[loss.name])
    assert not [x for x in res3.findings
                if x.code == "replicated-giant"]


def test_unregistered_prop_rule_warns_once():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()), \
            fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[8, 8], dtype="float32")
    b = main.global_block().desc
    b.vars["x"].sharding = [None, "mp", None]
    for i in range(2):
        b.add_var(VarDesc(f"frob_{i}", shape=[-1, 8, 8],
                          dtype="float32"))
        b.append_op(OpDesc("frobnicate", {"X": ["x"]},
                           {"Out": [f"frob_{i}"]}, {}))
    res = infer_sharding(main, options={"mesh_axes": {"mp": 2}})
    found = [f for f in res.findings
             if f.code == "unregistered-prop-rule"]
    assert len(found) == 1                    # once per op type
    assert found[0].severity == "warning"
    assert (found[0].block, found[0].op) == (0, 0)
    assert "frobnicate" in found[0].message
    # outputs degrade to replicated, not to garbage
    assert res.var_specs[(0, "frob_0")] == (None, None, None)


# ---------------------------------------------------------------------------
# propagation-rule sweep: cost-model coverage implies shardprop coverage
# ---------------------------------------------------------------------------

def test_every_cost_ruled_op_has_a_prop_rule():
    """Any op family important enough for a cost rule must either
    propagate shardings or be explicitly listed propagation-opaque —
    an unlisted gap silently drops layouts (the WARNING fixture
    above)."""
    missing = sorted(k for k in COST_RULES if not has_prop_rule(k))
    assert not missing, (
        f"{len(missing)} cost-ruled op type(s) have no sharding "
        f"propagation rule and are not PROPAGATION_OPAQUE: {missing}")
    # the opaque list is for ops whose outputs genuinely carry no
    # layout (metrics); it must not silently swallow compute ops
    assert PROPAGATION_OPAQUE <= {"accuracy"} | set(PROP_RULES) or \
        all(op not in PROP_RULES for op in PROPAGATION_OPAQUE)


def test_grad_ops_covered_by_generic_rule():
    assert has_prop_rule("mul_grad")
    assert has_prop_rule("layer_norm_grad")
    assert not has_prop_rule("frobnicate")


# ---------------------------------------------------------------------------
# sharding_pass: producer+consumer coordinates, deduped (satellite)
# ---------------------------------------------------------------------------

def test_producer_consumer_conflict_names_both_coordinates():
    main, _, loss, _, pg = _train_net()
    b = main.global_block().desc
    p = pg[0][0].name
    b.vars[p].sharding = ["mp", None]
    b.vars[p + "@GRAD"].sharding = [None, "mp"]
    diag = analyze_program(main, passes=("sharding",),
                           level="structural", fetch=[loss.name])
    found = diag.by_code("producer-consumer-conflict")
    assert len(found) == 1
    f = found[0]
    assert "(producer block" in f.message
    assert "(consumer block" in f.message
    assert f"op#{f.op}" in f.message          # consumer op named inline


def test_producer_consumer_conflict_dedupes_repeats():
    main = fluid.Program()
    b = main.global_block().desc
    b.add_var(VarDesc("a", shape=[4, 4], dtype="float32"))
    b.add_var(VarDesc("c", shape=[4, 4], dtype="float32"))
    b.vars["a"].sharding = ["mp", None]
    b.vars["c"].sharding = [None, "mp"]
    for _ in range(3):                        # while bodies clone ops
        b.append_op(OpDesc("assign", {"X": ["a"]}, {"Out": ["c"]}, {}))
    diag = analyze_program(main, passes=("sharding",),
                           level="structural")
    assert len(diag.by_code("producer-consumer-conflict")) == 1


# ---------------------------------------------------------------------------
# end-to-end wiring: LEVELS, comms consumption, plint exit codes
# ---------------------------------------------------------------------------

def test_shard_level_runs_and_comms_prices_inferred_graph():
    assert "shardprop" in LEVELS["shard"] and "comms" in LEVELS["shard"]
    main, _, loss, _, _ = _train_net()
    diag = main.analyze(level="shard", fetch_list=[loss],
                        options={"mesh_axes": {"dp": 2},
                                 "assume_batch": 8})
    assert not diag.has_errors, diag.render()
    sp = diag.reports["shardprop"]
    cm = diag.reports["comms"]
    # the comms pass priced shardprop's graph, not its heuristic scan
    assert cm["per_kind"]["all-reduce"]["count"] == \
        sp["per_kind"]["all-reduce"]["count"]
    assert cm["per_kind"]["all-reduce"]["payload_bytes"] == \
        sp["per_kind"]["all-reduce"]["payload_bytes"]
    assert cm["grad_sync_bytes"] > 0          # dW/db syncs flagged grad


def test_plint_shard_exit_codes(tmp_path, capsys):
    from paddle_tpu.tools import plint

    # clean dp training program -> 0
    main, _, loss, _, _ = _train_net()
    good = tmp_path / "good.json"
    good.write_bytes(main.desc.serialize_to_string())
    rc = plint.main([str(good), "--shard", "--mesh-axis", "dp=2",
                     "--assume-batch", "8", "--fetch", loss.name])
    capsys.readouterr()
    assert rc == 0

    # seeded resharding hazard -> 1, with coordinates in the output
    bad = fluid.Program()
    with fluid.program_guard(bad, fluid.Program()), \
            fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[8, 8], dtype="float32")
        y = fluid.layers.data(name="y", shape=[8, 8], dtype="float32")
        fluid.layers.elementwise_add(x, y)
    bb = bad.global_block().desc
    bb.vars["x"].sharding = [None, "mp", None]
    bb.vars["y"].sharding = [None, "np", None]
    badp = tmp_path / "bad.json"
    badp.write_bytes(bad.desc.serialize_to_string())
    rc = plint.main([str(badp), "--shard", "--mesh-axis", "mp=2",
                     "--mesh-axis", "np=2", "--json"])
    out = capsys.readouterr().out
    assert rc == 1
    payload = json.loads(out)
    hits = [f for f in payload["findings"]
            if f["code"] == "resharding-hazard"]
    assert hits and hits[0]["block"] == 0 and hits[0]["op"] == 0

    # giant threshold flag reaches the pass
    gp = tmp_path / "giant.json"
    gp.write_bytes(main.desc.serialize_to_string())
    rc = plint.main([str(gp), "--shard", "--mesh-axis", "model=2",
                     "--replicated-giant-bytes", "10000"])
    capsys.readouterr()
    assert rc == 1


# ---------------------------------------------------------------------------
# transpiler + registry integration
# ---------------------------------------------------------------------------

def test_transpiler_verifies_emitted_programs():
    main, _, loss, opt_ops, pg = _train_net()
    t = DistributeTranspiler()
    t.transpile(optimize_ops=opt_ops, params_grads=pg, trainers=2,
                program=main, mesh_axes={"dp": 2})
    assert t.get_trainer_program() is main
    assert not t.get_pserver_program().global_block().desc.ops


def test_transpiler_refuses_conflicting_plan():
    main, _, loss, opt_ops, pg = _train_net()
    b = main.global_block().desc
    p = pg[0][0].name
    b.vars[p].sharding = ["mp", None]
    b.vars[p + "@GRAD"].sharding = [None, "mp"]
    t = DistributeTranspiler()
    with pytest.raises(ProgramValidationError) as ei:
        t.transpile(optimize_ops=opt_ops, params_grads=pg, trainers=2,
                    program=main, mesh_axes={"dp": 2, "mp": 2})
    assert "producer-consumer-conflict" in str(ei.value)


def test_registry_shard_preflight(monkeypatch, tmp_path):
    from paddle_tpu.serving.gateway import registry as reg

    cfg = dict(KW, mesh_axes={"batch": 1, "model": 2})
    # a well-sharded manifest passes (no exception)
    reg.ModelRegistry._shard_preflight("generator", cfg)
    # engines and unsharded generators skip the preflight entirely
    reg.ModelRegistry._shard_preflight("engine", {"anything": 1})
    reg.ModelRegistry._shard_preflight("generator", dict(KW))

    # a manifest whose program fails propagation is refused
    bad = fluid.Program()
    with fluid.program_guard(bad, fluid.Program()), \
            fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        h = fluid.layers.fc(input=x, size=4, bias_attr=False)
    bb = bad.global_block().desc
    w = [n for n in bb.vars if n.endswith(".w_0")][0]
    bb.vars[w].sharding = ["model", None]
    bb.vars[h.name].persistable = True        # partial lands persistable
    monkeypatch.setattr(reg, "build_manifest_program",
                        lambda config, **kw: (bad, {"model": 2}))
    with pytest.raises(ProgramValidationError) as ei:
        reg.ModelRegistry._shard_preflight("generator", cfg)
    assert "partial-sum-unreduced" in str(ei.value)


# ---------------------------------------------------------------------------
# the differential gate: inferred graph == compiled-HLO ground truth
# ---------------------------------------------------------------------------

def _assert_differential(tag, prog, mesh_axes, feed, fetch_list, exe,
                         scope, mesh, mode, assume_batch):
    with fluid.scope_guard(scope), pmesh.mesh_guard(mesh):
        meas = exe.collective_analysis(prog, feed=feed,
                                       fetch_list=fetch_list, mode=mode)
    pred = infer_sharding(
        prog, options={"mesh_axes": mesh_axes,
                       "assume_batch": assume_batch},
        fetch=[getattr(v, "name", v) for v in fetch_list])
    errs = [f for f in pred.findings if f.severity == "error"]
    assert not errs, f"{tag}: " + "; ".join(f.render() for f in errs)
    cmp = compare_collectives(pred.per_kind(), meas["per_kind"])
    assert cmp["match"] and cmp["rel_err"] == 0.0, (
        f"{tag}: rel_err={cmp['rel_err']}\n"
        f"  predicted: {json.dumps(pred.per_kind(), sort_keys=True)}\n"
        f"  measured:  {json.dumps(meas['per_kind'], sort_keys=True)}")


@pytest.mark.parametrize("n", [2, 4])
def test_differential_sharded_decode_step(n):
    from paddle_tpu.serving.paged_decoder import PagedTransformerGenerator

    ma = {"batch": 1, "model": n}
    g = PagedTransformerGenerator(**KW, mesh_axes=ma)
    g.init_params(seed=1)
    g.open_slots(2)
    prog, _, next_ids, _ = g._unified
    feed = g._prefill_arrays()
    feed.update(g._decode_arrays(1))
    _assert_differential(f"decode model={n}", prog, ma, feed,
                         [next_ids], g.exe, g.scope, g.mesh, "infer", 2)


@pytest.mark.parametrize("n", [2, 4])
def test_differential_speculative_verify(n):
    from paddle_tpu.serving.paged_decoder import PagedTransformerGenerator
    from paddle_tpu.serving.speculative import SpeculativeGenerator

    ma = {"batch": 1, "model": n}
    tgt = PagedTransformerGenerator(**KW, mesh_axes=ma)
    drf = PagedTransformerGenerator(**KW, mesh_axes=ma,
                                    param_prefix="draft")
    sg = SpeculativeGenerator(tgt, drf, k=4)
    sg.init_params(seed=1)
    sg.open_slots(2)
    vprog, _, vnext, _ = sg._verify
    feed = tgt._prefill_arrays()
    feed.update(tgt._decode_arrays(sg.verify_tokens))
    feed["logit_mask"] = sg._vmask
    _assert_differential(f"verify model={n}", vprog, ma, feed, [vnext],
                         tgt.exe, tgt.scope, tgt.mesh, "infer", 2)


@pytest.mark.parametrize("n", [2, 4])
def test_differential_dp_training(n):
    main, startup, loss, opt_ops, pg = _train_net()
    t = DistributeTranspiler()
    t.transpile(optimize_ops=opt_ops, params_grads=pg, trainers=n,
                program=main, mesh_axes={"dp": n})
    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    mesh = pmesh.make_mesh({"dp": n})
    rng = np.random.RandomState(7)
    feed = {"x": rng.rand(8, 64).astype("float32"),
            "y": rng.randint(0, 10, (8, 1)).astype("int64")}
    _assert_differential(f"train dp={n}", t.get_trainer_program(),
                         {"dp": n}, feed, [loss], exe, scope, mesh,
                         "train", 8)
