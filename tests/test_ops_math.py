"""Op corpus tests, wave 1: math / elementwise / reductions / losses —
mirror of the reference's test_*_op.py files (test_mul_op.py,
test_elementwise_add_op.py, test_softmax_op.py, ...), built on the OpTest
harness's output + numeric-gradient checks."""

import numpy as np
import pytest

from op_test import OpTestCase

R = np.random.RandomState(7)


def _r(*shape):
    return R.uniform(0.1, 1.0, shape).astype(np.float32)


class TestMulOp:
    def test_output_and_grad(self):
        x, y = _r(4, 5), _r(5, 3)
        t = OpTestCase("mul", {"X": x, "Y": y})
        t.check_output({"Out": x @ y})
        t.check_grad(["X", "Y"])

    def test_flatten_dims(self):
        x, y = _r(2, 3, 4), _r(4, 6)
        t = OpTestCase("mul", {"X": x, "Y": y},
                       {"x_num_col_dims": 2, "y_num_col_dims": 1})
        t.check_output({"Out": (x.reshape(6, 4) @ y).reshape(2, 3, 6)})
        t.check_grad(["X", "Y"])


class TestMatmulOp:
    @pytest.mark.parametrize("tx,ty", [(False, False), (True, False),
                                       (False, True), (True, True)])
    def test_transposes(self, tx, ty):
        a = _r(3, 4) if not tx else _r(4, 3)
        b = _r(4, 5) if not ty else _r(5, 4)
        t = OpTestCase("matmul", {"X": a, "Y": b},
                       {"transpose_X": tx, "transpose_Y": ty})
        ax = a.T if tx else a
        bx = b.T if ty else b
        t.check_output({"Out": ax @ bx})
        t.check_grad(["X", "Y"])

    def test_batched(self):
        a, b = _r(2, 3, 4), _r(2, 4, 5)
        t = OpTestCase("matmul", {"X": a, "Y": b})
        t.check_output({"Out": a @ b})
        t.check_grad(["X", "Y"])


class TestElementwise:
    @pytest.mark.parametrize("op,fn", [
        ("elementwise_add", np.add), ("elementwise_sub", np.subtract),
        ("elementwise_mul", np.multiply), ("elementwise_div", np.divide),
        ("elementwise_max", np.maximum), ("elementwise_min", np.minimum),
    ])
    def test_same_shape(self, op, fn):
        x, y = _r(3, 4), _r(3, 4) + 0.5
        t = OpTestCase(op, {"X": x, "Y": y})
        t.check_output({"Out": fn(x, y)})
        t.check_grad(["X", "Y"])

    def test_broadcast_axis(self):
        x, y = _r(2, 3, 4), _r(3)
        t = OpTestCase("elementwise_add", {"X": x, "Y": y}, {"axis": 1})
        t.check_output({"Out": x + y.reshape(1, 3, 1)})
        t.check_grad(["X", "Y"])

    def test_trailing_broadcast(self):
        x, y = _r(2, 3, 4), _r(4)
        t = OpTestCase("elementwise_mul", {"X": x, "Y": y})
        t.check_output({"Out": x * y})
        t.check_grad(["X", "Y"])


class TestSumMeanScale:
    def test_sum_variadic(self):
        xs = [_r(3, 4) for _ in range(3)]
        t = OpTestCase("sum", {"X": xs})
        t.check_output({"Out": xs[0] + xs[1] + xs[2]})
        t.check_grad(["X"])

    def test_mean(self):
        x = _r(5, 6)
        t = OpTestCase("mean", {"X": x})
        t.check_output({"Out": x.mean()})
        t.check_grad(["X"])

    def test_scale(self):
        x = _r(4, 4)
        t = OpTestCase("scale", {"X": x}, {"scale": 2.5, "bias": 0.3})
        t.check_output({"Out": 2.5 * x + 0.3})
        t.check_grad(["X"])


class TestReduceOps:
    @pytest.mark.parametrize("op,fn", [
        ("reduce_sum", np.sum), ("reduce_mean", np.mean),
        ("reduce_max", np.max),
    ])
    def test_dim(self, op, fn):
        x = _r(3, 4, 5)
        t = OpTestCase(op, {"X": x}, {"dim": [1]})
        t.check_output({"Out": fn(x, axis=1)})
        if op != "reduce_max":
            t.check_grad(["X"])

    def test_keepdim_all(self):
        x = _r(3, 4)
        t = OpTestCase("reduce_sum", {"X": x},
                       {"reduce_all": True, "keep_dim": True})
        t.check_output({"Out": x.sum(keepdims=True).reshape(1, 1)})
        t.check_grad(["X"])


class TestActivations:
    @pytest.mark.parametrize("op,fn", [
        ("sigmoid", lambda x: 1 / (1 + np.exp(-x))),
        ("tanh", np.tanh),
        ("relu", lambda x: np.maximum(x, 0)),
        ("exp", np.exp),
        ("log", np.log),
        ("sqrt", np.sqrt),
        ("abs", np.abs),
        ("square", np.square),
        ("reciprocal", lambda x: 1 / x),
        ("softplus", lambda x: np.log1p(np.exp(x))),
        ("softsign", lambda x: x / (1 + np.abs(x))),
    ])
    def test_fwd_and_grad(self, op, fn):
        x = _r(4, 5) + 0.5  # positive domain for log/sqrt
        t = OpTestCase(op, {"X": x})
        t.check_output({"Out": fn(x)})
        t.check_grad(["X"])

    def test_leaky_relu(self):
        x = R.randn(4, 5).astype(np.float32)
        t = OpTestCase("leaky_relu", {"X": x}, {"alpha": 0.1})
        t.check_output({"Out": np.where(x > 0, x, 0.1 * x)})
        t.check_grad(["X"])


class TestSoftmaxAndLosses:
    def test_softmax(self):
        x = R.randn(5, 7).astype(np.float32)
        e = np.exp(x - x.max(-1, keepdims=True))
        t = OpTestCase("softmax", {"X": x})
        t.check_output({"Out": e / e.sum(-1, keepdims=True)})
        t.check_grad(["X"])

    def test_cross_entropy_hard(self):
        probs = _r(6, 4)
        probs /= probs.sum(-1, keepdims=True)
        label = R.randint(0, 4, (6, 1)).astype(np.int64)
        t = OpTestCase("cross_entropy", {"X": probs, "Label": label})
        exp = -np.log(np.take_along_axis(probs, label.astype(int), 1))
        t.check_output({"Out": exp})
        t.check_grad(["X"], max_relative_error=1e-2)

    def test_softmax_with_cross_entropy(self):
        logits = R.randn(6, 5).astype(np.float32)
        label = R.randint(0, 5, (6, 1)).astype(np.int64)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        loss = -np.log(np.take_along_axis(sm, label.astype(int), 1))
        t = OpTestCase("softmax_with_cross_entropy",
                       {"Logits": logits, "Label": label})
        t.check_output({"Softmax": sm, "Loss": loss})
        t.check_grad(["Logits"], output_slots=["Loss"])

    def test_sigmoid_ce_logits(self):
        x = R.randn(4, 3).astype(np.float32)
        lbl = R.randint(0, 2, (4, 3)).astype(np.float32)
        exp = np.maximum(x, 0) - x * lbl + np.log1p(np.exp(-np.abs(x)))
        t = OpTestCase("sigmoid_cross_entropy_with_logits",
                       {"X": x, "Label": lbl})
        t.check_output({"Out": exp})
        t.check_grad(["X"])

    def test_square_error_cost(self):
        x, y = _r(5, 3), _r(5, 3)
        t = OpTestCase("square_error_cost", {"X": x, "Y": y})
        t.check_output({"Out": (x - y) ** 2})
        t.check_grad(["X", "Y"])

    def test_huber_loss(self):
        x, y = _r(6, 1), _r(6, 1) * 3
        d = 1.0
        r = y - x
        exp = np.where(np.abs(r) <= d, 0.5 * r * r, d * (np.abs(r) - 0.5 * d))
        t = OpTestCase("huber_loss", {"X": x, "Y": y}, {"delta": d})
        t.check_output({"Out": exp})
        t.check_grad(["X"], output_slots=["Out"])


class TestTensorOps:
    def test_concat(self):
        xs = [_r(2, 3), _r(2, 4)]
        t = OpTestCase("concat", {"X": xs}, {"axis": 1})
        t.check_output({"Out": np.concatenate(xs, axis=1)})
        t.check_grad(["X"])

    def test_split(self):
        x = _r(2, 6)
        t = OpTestCase("split", {"X": x}, {"num": 3, "axis": 1},
                       n_outputs={"Out": 3})
        t.check_output({"Out": list(np.split(x, 3, axis=1))})
        t.check_grad(["X"])

    def test_transpose(self):
        x = _r(2, 3, 4)
        t = OpTestCase("transpose", {"X": x}, {"axis": [2, 0, 1]})
        t.check_output({"Out": x.transpose(2, 0, 1)})
        t.check_grad(["X"])

    def test_reshape(self):
        x = _r(2, 6)
        t = OpTestCase("reshape", {"X": x}, {"shape": [3, 4]})
        t.check_output({"Out": x.reshape(3, 4)})
        t.check_grad(["X"])

    def test_cast(self):
        x = _r(3, 3)
        t = OpTestCase("cast", {"X": x}, {"out_dtype": "int32"})
        t.check_output({"Out": x.astype(np.int32)})

    def test_lookup_table(self):
        w = _r(10, 4)
        ids = R.randint(0, 10, (5, 1)).astype(np.int64)
        t = OpTestCase("lookup_table", {"W": w, "Ids": ids})
        t.check_output({"Out": w[ids.squeeze(-1)]})
        t.check_grad(["W"])

    def test_top_k(self):
        x = R.randn(4, 9).astype(np.float32)
        t = OpTestCase("top_k", {"X": x}, {"k": 3})
        idx = np.argsort(-x, axis=1)[:, :3]
        vals = np.take_along_axis(x, idx, 1)
        t.check_output({"Out": vals, "Indices": idx.astype(np.int32)})

    def test_one_hot(self):
        ids = R.randint(0, 6, (5, 1)).astype(np.int64)
        t = OpTestCase("one_hot", {"X": ids}, {"depth": 6})
        exp = np.eye(6, dtype=np.float32)[ids.squeeze(-1)]
        t.check_output({"Out": exp})

    def test_gather(self):
        x = _r(8, 3)
        idx = np.array([0, 3, 7], np.int64)
        t = OpTestCase("gather", {"X": x, "Index": idx})
        t.check_output({"Out": x[[0, 3, 7]]})
        t.check_grad(["X"])

    def test_clip(self):
        x = R.randn(4, 4).astype(np.float32)
        t = OpTestCase("clip", {"X": x}, {"min": -0.3, "max": 0.4})
        t.check_output({"Out": np.clip(x, -0.3, 0.4)})
        t.check_grad(["X"])
