"""Quantized inference tests (ISSUE 7): the quantize/dequantize/
quantized_* op quartet (per-channel vs per-tensor scale shapes, the
inference-only no-grad exemption), the PTQ program-rewrite transform
(eligibility rules, output closeness, analyzer cleanliness incl. the
shape re-check actually re-running the quantized emitters), int8
save/load/merge round trips through io.py, the InferenceEngine
``quantize="int8"`` wire-through (private scope, quant stats,
0-recompile steady state), and the slow fixture-trained quality gates:
mnist top-1 and nmt BLEU through the quantized path must stay within a
stated tolerance of the float baseline."""

import os
import tempfile

import numpy as np
import pytest

from op_test import OpTestCase
from paddle_tpu import fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.transforms.quantize import (SCALE_SUFFIX,
                                                  quantize_program)
from paddle_tpu.serving import InferenceEngine


def _np_scale(x, axis=None):
    ax = np.abs(np.asarray(x, np.float32))
    amax = ax.max() if axis is None else \
        ax.max(axis=tuple(i for i in range(x.ndim) if i != axis))
    s = np.asarray(amax, np.float32) / 127.0
    return np.where(s == 0.0, np.float32(1.0), s).astype(np.float32)


def _np_quant(x, scale, axis=None):
    xf = np.asarray(x, np.float32)
    if axis is not None and np.ndim(scale) > 0:
        shape = [1] * xf.ndim
        shape[axis] = -1
        scale = scale.reshape(shape)
    return np.clip(np.round(xf / scale), -127, 127).astype(np.int8)


# ---------------------------------------------------------------------------
# ops
# ---------------------------------------------------------------------------

class TestQuantizeOps:
    def test_quantize_per_tensor(self):
        x = np.random.RandomState(0).randn(4, 6).astype(np.float32) * 3
        sc = _np_scale(x)
        OpTestCase("quantize", {"X": x}).check_output(
            {"Out": _np_quant(x, sc), "Scale": sc}, atol=0)

    def test_quantize_per_channel_scale_shape(self):
        """axis=1 -> one scale per output channel, shape [N] not []."""
        x = np.random.RandomState(1).randn(5, 3).astype(np.float32)
        x[:, 2] = 0.0                       # zero channel -> scale 1.0
        sc = _np_scale(x, axis=1)
        assert sc.shape == (3,) and sc[2] == 1.0
        case = OpTestCase("quantize", {"X": x}, attrs={"axis": 1})
        outs = case.run_all()
        got_q, got_s = outs["Out"][0], outs["Scale"][0]
        assert np.asarray(got_s).shape == (3,)
        np.testing.assert_array_equal(np.asarray(got_q), _np_quant(x, sc, 1))
        np.testing.assert_allclose(np.asarray(got_s), sc)

    def test_quantize_dequantize_roundtrip_error_bound(self):
        """|x - dq(q(x))| <= scale/2 elementwise — the exact-parity bound
        symmetric max-abs rounding guarantees (acceptance criterion)."""
        rng = np.random.RandomState(2)
        for axis in (None, 0, 1):
            x = (rng.randn(6, 8) * rng.uniform(0.1, 10)).astype(np.float32)
            sc = _np_scale(x, axis)
            q = _np_quant(x, sc, axis)
            attrs = {} if axis is None else {"axis": axis}
            deq = OpTestCase("dequantize", {"X": q, "Scale": sc},
                             attrs=attrs).run_single()
            deq = np.asarray(deq)
            bound = sc if axis is None else (
                sc[:, None] if axis == 0 else sc[None, :])
            assert (np.abs(deq - x) <= bound / 2 + 1e-7).all()

    def test_quantized_mul_per_channel(self):
        rng = np.random.RandomState(3)
        x = rng.randn(2, 3, 4).astype(np.float32)
        w = rng.randn(4, 5).astype(np.float32)
        sc = _np_scale(w, axis=1)
        q = _np_quant(w, sc, axis=1)
        want = (x.reshape(6, 4) @ (q.astype(np.float32))) * sc[None, :]
        OpTestCase("quantized_mul", {"X": x, "Y": q, "Scale": sc},
                   attrs={"x_num_col_dims": 2, "y_num_col_dims": 1}
                   ).check_output({"Out": want.reshape(2, 3, 5)})

    def test_quantized_mul_scalar_scale(self):
        rng = np.random.RandomState(4)
        x = rng.randn(3, 4).astype(np.float32)
        w = rng.randn(4, 5).astype(np.float32)
        sc = _np_scale(w)                   # per-tensor: 0-d scale
        q = _np_quant(w, sc)
        want = (x @ q.astype(np.float32)) * sc
        OpTestCase("quantized_mul", {"X": x, "Y": q, "Scale": sc}
                   ).check_output({"Out": want})

    def test_quantized_matmul_transpose_y(self):
        rng = np.random.RandomState(5)
        x = rng.randn(3, 4).astype(np.float32)
        w = rng.randn(5, 4).astype(np.float32)   # result col = w row
        sc = _np_scale(w, axis=0)
        q = _np_quant(w, sc, axis=0)
        want = (x @ q.astype(np.float32).T) * sc[None, :]
        OpTestCase("quantized_matmul", {"X": x, "Y": q, "Scale": sc},
                   attrs={"transpose_Y": True}).check_output({"Out": want})

    def test_quantized_matmul_batched(self):
        rng = np.random.RandomState(6)
        x = rng.randn(2, 3, 4).astype(np.float32)
        w = rng.randn(4, 5).astype(np.float32)
        sc = _np_scale(w, axis=1)
        q = _np_quant(w, sc, axis=1)
        want = x @ (q.astype(np.float32) * sc[None, :])
        OpTestCase("quantized_matmul", {"X": x, "Y": q, "Scale": sc}
                   ).check_output({"Out": want})

    def test_quantized_conv2d_matches_dequantized_conv(self):
        rng = np.random.RandomState(7)
        x = rng.randn(2, 3, 6, 6).astype(np.float32)
        w = rng.randn(4, 3, 3, 3).astype(np.float32)
        sc = _np_scale(w, axis=0)
        q = _np_quant(w, sc, axis=0)
        wf = q.astype(np.float32) * sc.reshape(-1, 1, 1, 1)
        ref = OpTestCase("conv2d", {"Input": x, "Filter": wf},
                         attrs={"strides": [1, 1], "paddings": [1, 1]}
                         ).run_single()
        OpTestCase("quantized_conv2d", {"Input": x, "Filter": q,
                                        "Scale": sc},
                   attrs={"strides": [1, 1], "paddings": [1, 1]}
                   ).check_output({"Output": np.asarray(ref)})

    def test_no_grad_exemption(self):
        """The quantized quartet is inference-only: append_backward
        skips them (no *_grad ops appear) while float paths around them
        still differentiate — the exemption the PTQ rewrite relies on."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup), fluid.unique_name.guard():
            x = layers.data("x", [4], "float32")
            x.stop_gradient = False
            y1 = layers.fc(input=x, size=3)
            q, sc = layers.quantize(x, axis=1)
            d = layers.dequantize(q, sc, axis=1)
            loss = layers.elementwise_add(layers.reduce_sum(y1),
                                          layers.reduce_sum(d))
            fluid.append_backward(loss)
        types = [op.type for op in main.global_block().ops]
        assert "quantize" in types and "dequantize" in types
        assert not any(t.startswith(("quantize_grad", "dequantize_grad",
                                     "quantized_")) and t.endswith("_grad")
                       for t in types), types
        # the float fc path still produced a gradient for x
        assert any(t == "mul_grad" for t in types), types


# ---------------------------------------------------------------------------
# the PTQ transform
# ---------------------------------------------------------------------------

def _fc_net(sizes=(16, 4)):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = layers.data("x", [6], "float32")
        h = x
        for i, s in enumerate(sizes[:-1]):
            h = layers.fc(input=h, size=s, act="relu")
        y = layers.fc(input=h, size=sizes[-1])
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    return main, scope, exe, y


class TestQuantizeProgram:
    def test_rewrite_outputs_close_and_stats(self):
        main, scope, exe, y = _fc_net()
        xv = np.random.RandomState(0).randn(8, 6).astype(np.float32)
        with fluid.scope_guard(scope):
            ref, = exe.run(main, feed={"x": xv}, fetch_list=[y],
                           mode="infer")
        stats = quantize_program(main, scope)
        assert stats.to_dict()["weights_quantized"] == 2
        assert stats.to_dict()["weight_bytes_saved"] > 0
        types = [op.type for op in main.global_block().ops]
        assert types.count("quantized_mul") == 2 and "mul" not in types
        with fluid.scope_guard(scope):
            got, = exe.run(main, feed={"x": xv}, fetch_list=[y],
                           mode="infer")
        ref, got = np.asarray(ref), np.asarray(got)
        assert np.abs(got - ref).max() <= 0.05 * max(1.0, np.abs(ref).max())
        # scope now holds int8 weights + fp32 sidecars under stable names
        for name in stats.quantized:
            assert np.asarray(scope.find_var(name)).dtype == np.int8
            assert np.asarray(scope.find_var(name + SCALE_SUFFIX)).dtype \
                == np.float32

    def test_shared_weight_is_skipped(self):
        """A weight with a non-quantizable reader keeps its float value —
        retyping it would corrupt the other consumer."""
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), fluid.unique_name.guard():
            x = layers.data("x", [4], "float32")
            w = fluid.ParamAttr(name="shared.w")
            h = layers.fc(input=x, size=4, bias_attr=False, param_attr=w)
            # same weight also read by an elementwise op
            wvar = main.global_block().vars["shared.w"]
            y = layers.elementwise_add(h, layers.reduce_sum(wvar))
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
        stats = quantize_program(main, scope)
        assert not stats.quantized
        assert "shared.w" in stats.skipped
        assert np.asarray(scope.find_var("shared.w")).dtype == np.float32

    def test_skip_and_min_elements(self):
        main, scope, exe, y = _fc_net()
        names = [op.input("Y")[0] for op in main.global_block().desc.ops
                 if op.type == "mul"]
        stats = quantize_program(main, scope, skip=[names[0]],
                                 min_elements=10**9)
        assert not stats.quantized
        assert stats.skipped[names[0]] == "explicitly skipped"
        assert "elements" in stats.skipped[names[1]]

    def test_quantize_weight_inside_while_body(self):
        """A weight consumed by a mul INSIDE a While sub-block — the
        shape of the whole NMT beam-decode step — quantizes like any
        global-block weight: the sub-block op is rewritten in place,
        the fp32 scale sidecar rides the while op's P slot into the
        body env, outputs stay close, and the rewritten program
        analyzes clean."""
        main, startup = fluid.Program(), fluid.Program()
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), fluid.unique_name.guard():
            x = layers.data("x", [6], "float32")
            i = layers.fill_constant(shape=[1], dtype="int64", value=0)
            i.stop_gradient = True
            n = layers.fill_constant(shape=[1], dtype="int64", value=3)
            n.stop_gradient = True
            acc = layers.fill_constant(shape=[1], dtype="float32",
                                       value=0.0)
            cond = layers.less_than(x=i, y=n)
            loop = layers.While(cond=cond)
            with loop.block():
                h = layers.fc(input=x, size=6, bias_attr=False,
                              param_attr=fluid.ParamAttr(name="loop.w"))
                layers.assign(layers.elementwise_add(
                    x=acc, y=layers.reduce_sum(h)), acc)
                layers.increment(x=i, in_place=True)
                layers.less_than(x=i, y=n, cond=cond)
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
        xv = np.random.RandomState(4).randn(3, 6).astype(np.float32)
        with fluid.scope_guard(scope):
            ref, = exe.run(main, feed={"x": xv}, fetch_list=[acc],
                           mode="infer")
        stats = quantize_program(main, scope)
        assert "loop.w" in stats.quantized, stats.skipped
        # the sub-block mul was rewritten and the sidecar routed via P
        sub_types = [od.type for b in main.desc.blocks[1:] for od in b.ops]
        assert "quantized_mul" in sub_types and "mul" not in sub_types
        while_op, = [od for od in main.global_block().desc.ops
                     if od.type == "while"]
        assert "loop.w" + SCALE_SUFFIX in while_op.inputs["P"]
        assert np.asarray(scope.find_var("loop.w")).dtype == np.int8
        with fluid.scope_guard(scope):
            got, = exe.run(main, feed={"x": xv}, fetch_list=[acc],
                           mode="infer")
        ref, got = np.asarray(ref), np.asarray(got)
        assert np.abs(got - ref).max() <= 0.05 * max(1.0, np.abs(ref).max())
        diag = main.analyze(level="full", fetch_list=[acc])
        assert not diag.has_errors, diag.render()

    def test_quantized_program_analyzes_clean(self):
        """Program.analyze(level='full') reports ZERO errors on the
        rewritten program AND the shape re-check actually re-ran the
        quantized emitters (no recheck-skipped info on them) — the
        acceptance criterion plus its teeth."""
        main, scope, exe, y = _fc_net()
        quantize_program(main, scope)
        diag = main.analyze(level="full", fetch_list=[y])
        assert not diag.has_errors, diag.render()
        skipped = [f for f in diag.findings
                   if f.code == "recheck-skipped"
                   and str(f.op_type).startswith(("quantize", "quantized_",
                                                  "dequantize"))]
        assert not skipped, [f.render() for f in skipped]

    def test_cast_bearing_mixed_dtype_has_no_false_positives(self):
        """bf16 AMP casts, int8 round trips and f64/i64 narrowing casts
        in one program: the dtype re-check must not flag the runtime's
        legitimate mixed-dtype promotions (ISSUE 7 satellite)."""
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup), fluid.unique_name.guard():
            x = layers.data("x", [6], "float32")
            xb = layers.cast(x, "bfloat16")
            h = layers.fc(input=xb, size=8)
            h32 = layers.cast(h, "float32")
            qi, sc = layers.quantize(h32, axis=1)
            dq = layers.dequantize(qi, sc, axis=1, out_dtype="float32")
            i64 = layers.cast(layers.argmax(dq, axis=-1), "int64")
            f64 = layers.cast(dq, "float64")
            z = layers.elementwise_add(layers.reduce_sum(f64),
                                       layers.cast(
                                           layers.reduce_sum(
                                               layers.cast(i64, "float32")),
                                           "float64"))
        diag = main.analyze(level="full", fetch_list=[z])
        assert not diag.has_errors, diag.render()


# ---------------------------------------------------------------------------
# io round trip
# ---------------------------------------------------------------------------

def test_int8_inference_model_round_trip(tmp_path):
    """save_inference_model -> load_inference_model keeps int8
    persistables int8 and the fp32 scale sidecars fp32, and the loaded
    program reproduces the quantized outputs bit-for-bit;
    merge_inference_model packs the same artifacts (ISSUE 7
    satellite)."""
    main, scope, exe, y = _fc_net()
    stats = quantize_program(main, scope)
    d = str(tmp_path / "model")
    xv = np.random.RandomState(1).randn(4, 6).astype(np.float32)
    with fluid.scope_guard(scope):
        ref, = exe.run(main, feed={"x": xv}, fetch_list=[y], mode="infer")
        fluid.io.save_inference_model(d, ["x"], [y], exe,
                                      main_program=main, scope=scope)
    files = set(os.listdir(d))
    for name in stats.quantized:
        assert name in files and name + SCALE_SUFFIX in files
    s2 = fluid.Scope()
    prog2, feeds, fetches = fluid.io.load_inference_model(
        d, exe, scope=s2, to_device=True)
    for name in stats.quantized:
        assert np.asarray(s2.find_var(name)).dtype == np.int8
        assert np.asarray(s2.find_var(name + SCALE_SUFFIX)).dtype \
            == np.float32
        assert prog2.global_block().desc.vars[name].dtype == "int8"
    with fluid.scope_guard(s2):
        got, = exe.run(prog2, feed={feeds[0]: xv}, fetch_list=fetches,
                       mode="infer")
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    merged = str(tmp_path / "model.merged")
    fluid.io.merge_inference_model(d, merged)
    assert os.path.getsize(merged) > 0


def test_int8_tensor_file_dtype_preserved(tmp_path):
    for dt in ("int8", "uint8"):
        a = np.arange(-6 if dt == "int8" else 0, 6,
                      dtype=dt).reshape(2, -1)
        p = str(tmp_path / f"t.{dt}")
        fluid.io.save_tensor(a, p)
        b = fluid.io.load_tensor(p)
        assert b.dtype == a.dtype
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# engine wire-through
# ---------------------------------------------------------------------------

class TestEngineQuantize:
    def _engine_pair(self):
        main, scope, exe, y = _fc_net()
        infer = fluid.io.prune_program(main, [y])
        base = InferenceEngine(program=infer, feed_names=["x"],
                               fetch_vars=[y], scope=scope, executor=exe,
                               batch_buckets=(4, 8), time_bucket=4)
        quant = InferenceEngine(program=infer, feed_names=["x"],
                                fetch_vars=[y], scope=scope, executor=exe,
                                batch_buckets=(4, 8), time_bucket=4,
                                quantize="int8")
        return base, quant, scope

    def test_outputs_close_and_caller_scope_untouched(self):
        base, quant, scope = self._engine_pair()
        xv = np.random.RandomState(2).randn(3, 6).astype(np.float32)
        ref, = base.infer({"x": xv})
        got, = quant.infer({"x": xv})
        assert np.abs(ref - got).max() <= \
            0.05 * max(1.0, np.abs(ref).max())
        # PTQ ran on PRIVATE copies: the shared trained scope keeps fp32
        for n in scope.vars:
            assert np.asarray(scope.find_var(n)).dtype != np.int8, n
        st = quant.cache_stats()["quant"]
        assert st["mode"] == "int8" and st["weights_quantized"] == 2
        assert st["weight_bytes_saved"] > 0
        assert base.cache_stats()["quant"] == {"mode": "off"}

    def test_zero_recompiles_after_warmup(self):
        _, quant, _ = self._engine_pair()
        rng = np.random.RandomState(3)
        feeds = [{"x": rng.randn(b, 6).astype(np.float32)}
                 for b in (2, 3, 4, 7)]
        quant.warmup(feeds)
        before = quant.cache_stats()["executable"]["misses"]
        for f in feeds * 3:
            quant.infer(f)
        after = quant.cache_stats()["executable"]["misses"]
        assert after - before == 0, (before, after)
        diag = quant.program.analyze(level="full")
        assert not diag.has_errors, diag.render()


# ---------------------------------------------------------------------------
# fixture-trained quality gates (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_mnist_top1_delta_through_quantized_path():
    """Train the book conv net on the committed digits fixture, then
    compare test top-1 through the float engine vs the int8-quantized
    engine: |delta| <= 0.02 (acceptance criterion tolerance)."""
    from paddle_tpu.datasets import mnist
    from paddle_tpu.models import recognize_digits

    train_rows = list(mnist.train()())
    test_rows = list(mnist.test()())
    if mnist.LAST_TIER not in ("real", "fixture"):
        pytest.skip("no real/fixture digits available")

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        img = layers.data("img", [1, 28, 28], "float32")
        label = layers.data("label", [1], "int64")
        pred, cost, _ = recognize_digits.conv_net(img, label)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(cost)
    xs = np.stack([r[0].reshape(1, 28, 28) for r in train_rows]) \
        .astype(np.float32)
    ys = np.asarray([r[1] for r in train_rows], np.int64).reshape(-1, 1)
    xt = np.stack([r[0].reshape(1, 28, 28) for r in test_rows]) \
        .astype(np.float32)
    yt = np.asarray([r[1] for r in test_rows], np.int64).reshape(-1, 1)
    exe = fluid.Executor(fluid.TPUPlace(0))
    bs = 128
    with fluid.scope_guard(scope):
        exe.run(startup)
        rng = np.random.RandomState(0)
        for _epoch in range(12):
            order = rng.permutation(len(xs))
            for i in range(0, len(xs) - bs + 1, bs):
                idx = order[i: i + bs]
                exe.run(main, feed={"img": xs[idx], "label": ys[idx]},
                        fetch_list=[cost])
    infer = fluid.io.prune_program(main, [pred])

    def top1(engine):
        correct = 0
        for i in range(0, len(xt), bs):
            p, = engine.infer({"img": xt[i:i + bs]})
            correct += int((np.asarray(p).argmax(-1)
                            == yt[i:i + bs, 0]).sum())
        return correct / len(xt)

    kw = dict(program=infer, feed_names=["img"], fetch_vars=[pred],
              scope=scope, executor=exe, batch_buckets=(32, 64, bs))
    base = top1(InferenceEngine(**kw))
    quant = top1(InferenceEngine(quantize="int8", **kw))
    print(f"mnist top-1 float={base:.4f} int8={quant:.4f}")
    assert base > 0.5, f"baseline degenerate ({base}) — gate meaningless"
    assert abs(base - quant) <= 0.02, (base, quant)


@pytest.mark.slow
def test_nmt_bleu_delta_through_quantized_path():
    """Train the attention seq2seq briefly on the committed CLDR corpus
    fixture and compare held-out corpus BLEU of beam decodes through the
    float engine vs the int8 engine: |delta| <= 0.05 (acceptance
    criterion tolerance)."""
    from paddle_tpu.datasets import wmt16
    from paddle_tpu.fluid.core.lod import make_seq
    from paddle_tpu.models import machine_translation as mt
    from paddle_tpu.utils.bleu import corpus_bleu

    dict_size = 2000
    train_rows = list(wmt16.train(dict_size, dict_size)())[:2048]
    test_rows = list(wmt16.test(dict_size, dict_size)())[:128]
    if wmt16.LAST_TIER not in ("real", "fixture"):
        pytest.skip("no real/fixture corpus available")

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        src = layers.data("src", [1], "int64", lod_level=1)
        trg = layers.data("trg", [1], "int64", lod_level=1)
        nxt = layers.data("nxt", [1], "int64", lod_level=1)
        avg_cost, _ = mt.attention_train_model(src, trg, nxt, dict_size,
                                               word_dim=64, hidden_dim=128)
        fluid.optimizer.Adam(learning_rate=2e-3).minimize(avg_cost)
        ids_out, _ = mt.attention_decode_model(
            src, dict_size, word_dim=64, hidden_dim=128, beam_size=3,
            max_length=16)

    def batch(rs):
        return (make_seq([r[0] for r in rs], dtype=np.int64, bucket=8),
                make_seq([r[1] for r in rs], dtype=np.int64, bucket=8),
                make_seq([r[2] for r in rs], dtype=np.int64, bucket=8))

    exe = fluid.Executor(fluid.TPUPlace(0))
    bs = 64
    with fluid.scope_guard(scope):
        exe.run(startup)
        rng = np.random.RandomState(0)
        for _epoch in range(3):
            order = rng.permutation(len(train_rows))
            for i in range(0, len(train_rows) - bs + 1, bs):
                s, n, t = batch([train_rows[j] for j in order[i:i + bs]])
                exe.run(main, feed={"src": s, "trg": t, "nxt": n},
                        fetch_list=[avg_cost])
    infer = fluid.io.prune_program(main, [ids_out])

    def bleu(engine):
        hyps, refs = [], []
        for i in range(0, len(test_rows), bs):
            s, n, _ = batch(test_rows[i:i + bs])
            out, = engine.infer({"src": s}, return_numpy=False)
            best = np.asarray(out)[:, 0]
            for b in range(best.shape[0]):
                hyps.append([int(w) for w in best[b] if w > 1])
                refs.append([[int(w) for w in np.asarray(n.data)[b]
                              if w > 1]])
        return float(corpus_bleu(hyps, refs, smooth=True))

    kw = dict(program=infer, feed_names=["src"], fetch_vars=[ids_out],
              scope=scope, executor=exe, batch_buckets=(32, bs),
              time_bucket=8)
    base = bleu(InferenceEngine(**kw))
    quant = bleu(InferenceEngine(quantize="int8", **kw))
    print(f"nmt BLEU float={base:.4f} int8={quant:.4f}")
    assert abs(base - quant) <= 0.05, (base, quant)
