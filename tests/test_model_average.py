"""ModelAverage (parameter averaging) — reference
paddle/parameter/AverageOptimizer.h + doc/design/parameter_average.md.

The window bookkeeping is asserted against an exact numpy simulation of
the documented update rule, and apply()/restore() are asserted to swap
the averaged weights in and back out of the scope.
"""

import numpy as np

from paddle_tpu import fluid


def _simulate(p0, grads, lr, rate, min_win, max_win):
    """Numpy twin of sgd + average_accumulates (kmax flush elided: tests
    stay far below 16384 updates)."""
    p = p0.copy()
    s1 = np.zeros_like(p)
    s2 = np.zeros_like(p)
    s3 = np.zeros_like(p)
    n_acc = old_acc = n_upd = 0
    for g in grads:
        p = p - lr * g
        n_upd += 1
        n_acc += 1
        s1 = s1 + p
        window = min(max_win, int(n_upd * rate))
        if n_acc >= min_win and n_acc >= window:
            s3 = s1 + s2
            s1 = np.zeros_like(p)
            s2 = np.zeros_like(p)
            old_acc, n_acc = n_acc, 0
    avg = (s1 + s2 + s3) / max(n_acc + old_acc, 1)
    return p, avg


def test_model_average_matches_simulation(fresh_programs):
    main, startup, scope = fresh_programs
    lr, rate, min_win, max_win = 0.1, 1.0, 2, 4
    x = fluid.layers.data("x", [3], "float32")
    y = fluid.layers.data("y", [1], "float32")
    pred = fluid.layers.fc(x, size=1, bias_attr=False,
                           param_attr=fluid.ParamAttr(name="w"))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    model_avg = fluid.optimizer.ModelAverage(
        average_window_rate=rate, min_average_window=min_win,
        max_average_window=max_win, main_program=main,
        startup_program=startup)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    xs = rng.rand(4, 3).astype(np.float32)
    ys = rng.rand(4, 1).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        w0 = np.asarray(scope.find_var("w"))
        grads = []
        for _ in range(7):
            w_before = np.asarray(scope.find_var("w"))
            exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
            w_after = np.asarray(scope.find_var("w"))
            grads.append((w_before - w_after) / lr)   # observed gradient
        w_raw = np.asarray(scope.find_var("w"))
        p_sim, avg_sim = _simulate(w0, grads, lr, rate, min_win, max_win)
        np.testing.assert_allclose(w_raw, p_sim, rtol=1e-5, atol=1e-6)
        with model_avg.apply(exe):
            w_avg = np.asarray(scope.find_var("w"))
            np.testing.assert_allclose(w_avg, avg_sim, rtol=1e-5,
                                       atol=1e-6)
            assert not np.allclose(w_avg, w_raw)   # averaging did something
        w_back = np.asarray(scope.find_var("w"))
        np.testing.assert_allclose(w_back, w_raw, rtol=0, atol=0)
        # manual apply without restore, then explicit restore
        with model_avg.apply(exe, need_restore=False):
            pass
        np.testing.assert_allclose(
            np.asarray(scope.find_var("w")), avg_sim,
            rtol=1e-5, atol=1e-6)
        model_avg.restore(exe)
        np.testing.assert_allclose(
            np.asarray(scope.find_var("w")), w_raw,
            rtol=0, atol=0)


def test_v2_model_average_on_book_config():
    """The v2 surface (reference settings ... model_average on the
    optimizer): a book-style config trains with averaging on, and the
    averaged weights differ from the raw ones for inference."""
    import paddle_tpu.v2 as paddle

    paddle.init(seed=7)
    x = paddle.layer.data(name="x",
                          type=paddle.data_type.dense_vector(8))
    y = paddle.layer.data(name="y",
                          type=paddle.data_type.integer_value(2))
    h = paddle.layer.fc(input=x, size=16,
                        act=paddle.activation.Tanh())
    pred = paddle.layer.fc(input=h, size=2,
                           act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=pred, label=y)
    parameters = paddle.parameters.create(cost)
    opt = paddle.optimizer.Momentum(
        learning_rate=0.05, momentum=0.9,
        model_average=paddle.optimizer.ModelAverage(
            average_window=1.0, min_average_window=2,
            max_average_window=6))
    trainer = paddle.trainer.SGD(cost=cost, parameters=parameters,
                                 update_equation=opt)
    assert trainer.model_average is not None
    rng = np.random.RandomState(11)

    def reader():
        for _ in range(24):
            v = rng.rand(8).astype(np.float32)
            yield v, int(v.sum() > 4.0)

    trainer.train(reader=paddle.batch(reader, 8), num_passes=3,
                  feeding={"x": 0, "y": 1})
    scope = parameters.scope
    exe = trainer.__exe__
    with fluid.scope_guard(scope):
        from paddle_tpu.fluid.framework import Parameter

        prog = trainer.__topology__
        pnames = [n for n, v in prog.global_block().vars.items()
                  if isinstance(v, Parameter)]
        raw = {n: np.asarray(scope.find_var(n)) for n in pnames}
        with trainer.model_average.apply(exe):
            avg = {n: np.asarray(scope.find_var(n))
                   for n in pnames}
        back = {n: np.asarray(scope.find_var(n))
                for n in pnames}
    changed = any(not np.allclose(raw[n], avg[n]) for n in pnames)
    assert changed, "averaging should move at least one weight"
    for n in pnames:
        np.testing.assert_array_equal(raw[n], back[n])
