"""Sequence stack tests: SeqArray feeding, sequence ops, lod-aware fc/
embedding, and RNG-salt determinism of recomputed grads."""

import numpy as np

from paddle_tpu import fluid
from paddle_tpu.fluid import make_seq


def test_make_seq_and_mask():
    s = make_seq([[1, 2, 3], [4]], dtype=np.int32, bucket=4)
    assert s.data.shape == (2, 4)
    np.testing.assert_array_equal(s.lengths, [3, 1])
    np.testing.assert_array_equal(np.asarray(s.mask(np.int32)),
                                  [[1, 1, 1, 0], [1, 0, 0, 0]])


def test_sequence_pool_types(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.layers.data(name="x", shape=[2], dtype="float32", lod_level=1)
    pools = {pt: fluid.layers.sequence_pool(x, pt)
             for pt in ["sum", "average", "max", "last", "first"]}
    exe = fluid.Executor(fluid.CPUPlace())
    seqs = [np.array([[1., 2.], [3., 4.], [5., 6.]]),
            np.array([[7., 8.]])]
    feed = {"x": make_seq(seqs, dtype=np.float32)}
    outs = exe.run(main, feed=feed, fetch_list=list(pools.values()))
    res = dict(zip(pools, outs))
    np.testing.assert_allclose(res["sum"], [[9, 12], [7, 8]])
    np.testing.assert_allclose(res["average"], [[3, 4], [7, 8]])
    np.testing.assert_allclose(res["max"], [[5, 6], [7, 8]])
    np.testing.assert_allclose(res["last"], [[5, 6], [7, 8]])
    np.testing.assert_allclose(res["first"], [[1, 2], [7, 8]])


def test_sequence_softmax_masks_padding(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.layers.data(name="x", shape=[1], dtype="float32", lod_level=1)
    sm = fluid.layers.sequence_softmax(x)
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {"x": make_seq([np.zeros((3, 1)), np.zeros((1, 1))],
                          dtype=np.float32)}
    out, = exe.run(main, feed=feed, fetch_list=[sm], return_numpy=False)
    data = np.asarray(out.data).squeeze(-1)
    np.testing.assert_allclose(data[0, :3], [1 / 3] * 3, rtol=1e-5)
    assert data[0, 3:].sum() == 0         # padding got zero probability
    np.testing.assert_allclose(data[1, 0], 1.0, rtol=1e-5)


def test_embedding_seq_pipeline_trains(fresh_programs):
    """word2vec-style slice: embedding -> sequence_pool -> fc -> CE loss."""
    main, startup, scope = fresh_programs
    words = fluid.layers.data(name="w", shape=[1], dtype="int64", lod_level=1)
    label = fluid.layers.data(name="y", shape=[1], dtype="int64")
    emb = fluid.layers.embedding(input=words, size=[50, 8])
    pooled = fluid.layers.sequence_pool(emb, "average")
    logits = fluid.layers.fc(input=pooled, size=4)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(3)
    losses = []
    for _ in range(40):
        seqs = [rng.randint(0, 50, size=(rng.randint(2, 7), 1))
                for _ in range(8)]
        lbl = np.array([[s.sum() % 4] for s in seqs], dtype=np.int64)
        feed = {"w": make_seq(seqs, dtype=np.int32, bucket=8), "y": lbl}
        lv, = exe.run(main, feed=feed, fetch_list=[loss])
        losses.append(float(lv))
    assert np.mean(losses[-8:]) < np.mean(losses[:8])


def test_fc_on_sequence_has_full_bias(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.layers.data(name="x", shape=[3], dtype="float32", lod_level=1)
    h = fluid.layers.fc(input=x, size=5)
    biases = [p for p in main.global_block().all_parameters()
              if tuple(p.shape) == (5,)]
    assert len(biases) == 1  # bias must be [size], not a 0-d scalar
    params = {tuple(p.shape) for p in main.global_block().all_parameters()}
    assert (3, 5) in params and (5,) in params


def test_dropout_grad_mask_determinism(fresh_programs):
    """The vjp-recomputed dropout in the grad op must regenerate the same
    mask (RNG salt contract, lowering.py)."""
    main, startup, scope = fresh_programs
    x = fluid.layers.data(name="x", shape=[64], dtype="float32")
    x.stop_gradient = False
    d = fluid.layers.dropout(x, dropout_prob=0.5)
    loss = fluid.layers.reduce_sum(d)
    fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((4, 64), np.float32)
    out, gx = exe.run(main, feed={"x": xv}, fetch_list=[d, x.grad_name])
    # gradient of sum(dropout(x)) wrt x is exactly the scaled keep-mask;
    # if the grad op's RNG disagreed with the forward, these would differ
    np.testing.assert_allclose(gx, out, rtol=1e-6)
    assert set(np.unique(out)) == {0.0, 2.0}


def test_sequence_conv_shapes(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.layers.data(name="x", shape=[4], dtype="float32", lod_level=1)
    c = fluid.layers.sequence_conv(x, num_filters=6, filter_size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": make_seq([np.ones((5, 4)), np.ones((2, 4))],
                          dtype=np.float32)}
    out, = exe.run(main, feed=feed, fetch_list=[c], return_numpy=False)
    assert np.asarray(out.data).shape == (2, 5, 6)
    # padding rows must stay zero
    assert np.abs(np.asarray(out.data)[1, 2:]).sum() == 0


def test_sequence_concat_time_axis(fresh_programs):
    """axis=0 (reference seq_concat_layer default): per-row end-to-end
    time join, lengths add, padding stays zero."""
    from paddle_tpu.fluid.core.lod import SeqArray, make_seq

    main, startup, scope = fresh_programs
    a = fluid.layers.data(name="a", shape=[1], dtype="float32",
                          lod_level=1)
    b = fluid.layers.data(name="b", shape=[1], dtype="float32",
                          lod_level=1)
    out = fluid.layers.sequence_concat([a, b], axis=0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    av = make_seq([[1, 2], [3]], dtype=np.float32)
    bv = make_seq([[7], [8, 9]], dtype=np.float32, bucket=3)
    res, = exe.run(main, feed={"a": av, "b": bv}, fetch_list=[out],
                   return_numpy=False)
    assert isinstance(res, SeqArray)
    np.testing.assert_array_equal(np.asarray(res.lengths), [3, 3])
    d = np.asarray(res.data)
    np.testing.assert_allclose(d[0][:3], [1, 2, 7])
    np.testing.assert_allclose(d[1][:3], [3, 8, 9])
    np.testing.assert_allclose(d[:, 3:], 0)


def test_lambda_rank_cost_matches_naive_and_trains(fresh_programs):
    """LambdaRank cost (reference gserver LambdaCost) — value parity
    against an O(n^2) numpy pair loop, and training a linear scorer on
    mq2007-style features improves NDCG@3."""
    main, startup, scope = fresh_programs
    sc = fluid.layers.data("sc", [1], "float32", lod_level=1)
    lb = fluid.layers.data("lb", [1], "float32", lod_level=1)
    cost = fluid.layers.lambda_rank_cost(sc, lb, ndcg_num=3)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    seqs_s = [rng.randn(5, 1).astype(np.float32),
              rng.randn(3, 1).astype(np.float32)]
    seqs_l = [np.array([[2], [0], [1], [0], [2]], np.float32),
              np.array([[1], [0], [0]], np.float32)]
    with fluid.scope_guard(scope):
        exe.run(startup)
        got, = exe.run(main, feed={"sc": make_seq(seqs_s),
                                   "lb": make_seq(seqs_l)},
                       fetch_list=[cost])

    def naive(s, l, K=3):
        s, l = s.ravel(), l.ravel()
        n = len(s)
        order = np.argsort(-s)
        ranks = np.argsort(order)
        gain = 2.0 ** l - 1
        disc = np.where(ranks < K, 1 / np.log2(2 + ranks), 0.0)
        ideal = np.sort(l)[::-1]
        maxdcg = sum((2.0 ** ideal[r] - 1) / np.log2(2 + r)
                     for r in range(min(K, n)))
        if maxdcg <= 0:
            return 0.0
        out = 0.0
        for i in range(n):
            for j in range(n):
                if l[i] > l[j]:
                    dn = abs((gain[i] - gain[j]) *
                             (disc[i] - disc[j])) / maxdcg
                    out += dn * np.log1p(np.exp(-(s[i] - s[j])))
        return out

    want = np.array([[naive(seqs_s[0], seqs_l[0])],
                     [naive(seqs_s[1], seqs_l[1])]])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                               atol=1e-6)

    # training: linear scorer over 4 features; relevance = x @ w_true
    main2, startup2 = fluid.Program(), fluid.Program()
    scope2 = fluid.Scope()
    with fluid.program_guard(main2, startup2), fluid.unique_name.guard():
        x = fluid.layers.data("x", [4], "float32", lod_level=1)
        rel = fluid.layers.data("rel", [1], "float32", lod_level=1)
        score = fluid.layers.fc(input=x, size=1, bias_attr=False)
        # through the v2 wrapper so its Score/Label wiring is covered
        import paddle_tpu.v2 as _p2

        c2 = _p2.layer.lambda_cost(input=score, score=rel, NDCG_num=3)
        fluid.optimizer.Adam(learning_rate=0.05).minimize(c2)
    w_true = np.array([1.0, -0.5, 0.3, 0.8], np.float32)
    qs, rels = [], []
    for _ in range(8):
        docs = rng.randn(6, 4).astype(np.float32)
        r = (docs @ w_true)
        lvl = np.digitize(r, np.quantile(r, [0.5, 0.85])).astype(
            np.float32).reshape(-1, 1)
        qs.append(docs)
        rels.append(lvl)

    def ndcg3(w):
        total = 0.0
        for docs, lvl in zip(qs, rels):
            s = docs @ w
            order = np.argsort(-s.ravel())
            dcg = sum((2 ** lvl.ravel()[order[r]] - 1) / np.log2(2 + r)
                      for r in range(3))
            ideal = np.sort(lvl.ravel())[::-1]
            idcg = sum((2 ** ideal[r] - 1) / np.log2(2 + r)
                       for r in range(3))
            total += dcg / max(idcg, 1e-9)
        return total / len(qs)

    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope2):
        exe2.run(startup2)
        w0 = np.asarray(scope2.find_var("fc_0.w_0")).ravel().copy()
        for _ in range(60):
            exe2.run(main2, feed={"x": make_seq(qs),
                                  "rel": make_seq(rels)},
                     fetch_list=[c2])
        w1 = np.asarray(scope2.find_var("fc_0.w_0")).ravel()
    assert ndcg3(w1) > ndcg3(w0) + 0.1, (ndcg3(w0), ndcg3(w1))
    assert ndcg3(w1) > 0.85, ndcg3(w1)
