"""COMPAT.md is a checked contract, not prose: every row whose status
is `v2` must resolve to a real callable at the claimed surface
(paddle.layer / paddle.networks), and the counts line must match the
table.  A rename or removal that silently breaks the import-swap claim
fails here.
"""

import re
from pathlib import Path

import paddle_tpu.v2 as paddle

COMPAT = Path(__file__).resolve().parent.parent / "COMPAT.md"

ROW = re.compile(r"\| (\d+) \| (\S+) \| (\w+) \| (.*) \|$")

# table name -> attribute looked up (the v2 re-export strips `_layer`;
# a handful of rows document their surface name in the Where column)
SPECIAL = {
    "kmax_seq_score_layer": "kmax_seq_score",
    "square_error_cost": "mse_cost",
    "cross_entropy": "cross_entropy_cost",
    "conv_operator": "conv_projection",
    "warp_ctc_layer": "ctc",
    "lambda_cost": "lambda_cost",
    "huber_regression_cost": "huber_regression_cost",
    "huber_classification_cost": "huber_classification_cost",
    "img_conv_layer": "img_conv",
    "img_pool_layer": "img_pool",
    "pooling_layer": "pool",
    "maxid_layer": "max_id",
}


def _rows():
    layers, networks, section = [], [], None
    for line in COMPAT.read_text().splitlines():
        if line.startswith("## layers.py"):
            section = layers
        elif line.startswith("## networks.py"):
            section = networks
        m = ROW.match(line)
        if m and section is not None:
            section.append((int(m.group(1)), m.group(2),
                            m.group(3), m.group(4)))
    return layers, networks


def _surface_name(table_name, where):
    if table_name in SPECIAL:
        return SPECIAL[table_name]
    # rows usually name the surface fn in backticks first
    m = re.search(r"`(?:networks\.|layer\.)?([A-Za-z_][A-Za-z0-9_]*)`",
                  where)
    if m:
        return m.group(1)
    name = table_name
    if name.endswith("_layer"):
        name = name[: -len("_layer")]
    return name


def test_layers_rows_resolve():
    layers, _ = _rows()
    assert len(layers) == 106, f"expected 106 layer rows, got {len(layers)}"
    missing = []
    for num, name, status, where in layers:
        if status != "v2":
            continue
        attr = _surface_name(name, where)
        if not (hasattr(paddle.layer, attr)
                or hasattr(paddle.networks, attr)):
            missing.append((num, name, attr))
    assert not missing, f"COMPAT v2 rows without a real surface: {missing}"


def test_networks_rows_resolve():
    _, networks = _rows()
    assert len(networks) == 21, \
        f"expected 21 network rows, got {len(networks)}"
    missing = []
    for num, name, status, where in networks:
        if status != "v2":
            continue
        attr = _surface_name(name, where)
        if not (hasattr(paddle.networks, attr)
                or hasattr(paddle.layer, attr)):
            missing.append((num, name, attr))
    assert not missing, f"COMPAT v2 rows without a real surface: {missing}"


def test_counts_line_matches_table():
    layers, networks = _rows()
    text = COMPAT.read_text()
    m = re.search(r"Counts: (\d+) v2 \+ (\d+) fluid \+ (\d+) superseded "
                  r"\+ (\d+) absent", text)
    assert m, "counts line missing"
    from collections import Counter

    c = Counter(status for _, _, status, _ in layers)
    assert (int(m.group(1)), int(m.group(2)), int(m.group(3)),
            int(m.group(4))) == (c["v2"], c["fluid"], c["superseded"],
                                 c["absent"])
    mn = re.search(r"networks\.py: (\d+) v2 \+ (\d+) superseded", text)
    assert mn, "networks counts missing"
    cn = Counter(status for _, _, status, _ in networks)
    assert (int(mn.group(1)), int(mn.group(2))) == (cn["v2"],
                                                    cn["superseded"])


def test_no_absent_rows_remain():
    layers, networks = _rows()
    absent = [(n, name) for n, name, status, _ in layers + networks
              if status == "absent"]
    assert absent == [], f"absent rows resurfaced: {absent}"
