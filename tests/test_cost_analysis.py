"""The static cost analyzer (ISSUE 11): per-op cost-rule goldens
(through the op_test harness), the liveness byte-timeline planner with
exact peak coordinates, donation-aware aliasing, budget gating, the
recompile-hazard lint + bucket enumeration, the sharded comms
estimator, level-keyed preflight counters, and the serving wiring
(registry static costing, scheduler HBM budget, engine bucket set).
"""

import json
import os

import numpy as np
import pytest

from paddle_tpu import fluid
from paddle_tpu.fluid.analysis.cost import (CostEnv, get_chip, op_cost,
                                            plan_program, roofline)
from paddle_tpu.fluid.analysis.dataflow import ProgramView
from paddle_tpu.fluid.core.desc import OpDesc, VarDesc

from op_test import OpTestCase


# ---------------------------------------------------------------------------
# op-level cost-rule goldens (satellite: mul/matmul/conv2d/cache ops)
# ---------------------------------------------------------------------------

def test_mul_cost_golden():
    t = OpTestCase("mul", {
        "X": np.ones((4, 8), np.float32),
        "Y": np.ones((8, 16), np.float32)})
    # 2*M*N*K fused multiply-adds counted as 2 flops each
    t.check_cost(expect_flops=2.0 * 4 * 16 * 8,
                 expect_bytes_read=(4 * 8 + 8 * 16) * 4,
                 expect_bytes_written=4 * 16 * 4)


def test_matmul_cost_golden():
    t = OpTestCase("matmul", {
        "X": np.ones((2, 4, 8), np.float32),
        "Y": np.ones((2, 8, 16), np.float32)})
    t.check_cost(expect_flops=2.0 * (2 * 4 * 16) * 8,
                 expect_bytes_read=(2 * 4 * 8 + 2 * 8 * 16) * 4,
                 expect_bytes_written=2 * 4 * 16 * 4)


def test_conv2d_cost_golden():
    t = OpTestCase("conv2d", {
        "Input": np.ones((2, 3, 8, 8), np.float32),
        "Filter": np.ones((4, 3, 3, 3), np.float32)},
        attrs={"strides": [1, 1], "paddings": [1, 1]})
    out_elems = 2 * 4 * 8 * 8            # SAME-padded spatial extent
    t.check_cost(expect_flops=2.0 * out_elems * 3 * 3 * 3,
                 expect_bytes_read=(2 * 3 * 8 * 8 + 4 * 3 * 3 * 3) * 4,
                 expect_bytes_written=out_elems * 4)


def test_cache_write_cost_golden():
    """Out aliases Cache under donation: only the written slice and the
    index move — the cache tensor itself is free."""
    t = OpTestCase("cache_write", {
        "Cache": np.zeros((2, 16, 2, 4), np.float32),
        "Value": np.ones((2, 1, 2, 4), np.float32),
        "Index": np.zeros(1, np.int32)},
        attrs={"axis": 1})
    t.check_cost(expect_flops=0.0,
                 expect_bytes_read=2 * 1 * 2 * 4 * 4 + 4,
                 expect_bytes_written=2 * 1 * 2 * 4 * 4)


def test_quantized_paged_cache_write_int8_sidecar_golden():
    """The int8 pool write prices the quantize math AND the fp32 block
    scales (2 roles x B*C tokens x 4 bytes) the sidecar stores."""
    n_pages, n_layer, page, h, d = 4, 1, 4, 2, 4
    rows = n_pages * n_layer * 2
    t = OpTestCase("quantized_paged_cache_write", {
        "Pool": np.zeros((h, rows, page, d), np.int8),
        "Scales": np.zeros((1, rows, page), np.float32),
        "K": np.ones((2, 1, h, d), np.float32),
        "V": np.ones((2, 1, h, d), np.float32),
        "Pages": np.ones((2, 1), np.int32),
        "Offsets": np.zeros((2, 1), np.int32)},
        attrs={"layer": 0, "n_layer": n_layer},
        # skip the output-discovery probe: the emitter's functional
        # scatter needs jax arrays, and the cost rule only reads descs
        n_outputs={"Out": 1, "ScalesOut": 1})
    kv_elems = 2 * (2 * 1 * h * d)
    t.check_cost(
        expect_flops=6.0 * kv_elems,
        # K+V fp32 reads + page/offset vectors (never the donated pool)
        expect_bytes_read=kv_elems * 4 + 2 * 1 * 4 * 2,
        # int8 bytes land at 1 byte/elem + 2 fp32 scales per token
        expect_bytes_written=kv_elems * 1 + 2 * (2 * 1) * 4)


def test_ragged_decode_attention_cost_golden():
    """Reads price the page-table-addressable pool span (K+V at the
    pool's int8 itemsize) plus the fp32 scale sidecar rows."""
    n_pages, n_layer, page, h, d = 4, 1, 4, 2, 4
    rows = n_pages * n_layer * 2
    b, c, p = 2, 1, 2
    t = OpTestCase("ragged_decode_attention", {
        "Q": np.ones((b, c, h, d), np.float32),
        "Pool": np.zeros((h, rows, page, d), np.int8),
        "PageTable": np.ones((b, p), np.int32),
        "Lengths": np.ones(b, np.int32),
        "QBase": np.zeros(b, np.int32),
        "Scales": np.zeros((1, rows, page), np.float32)},
        attrs={"layer": 0, "n_layer": n_layer, "causal": True})
    lmax = p * page
    reads = (2.0 * b * p * page * h * d * 1      # int8 K+V pages
             + b * c * h * d * 4                 # Q
             + b * p * 4 + b * 4                 # table + lengths
             + 2.0 * b * p * page * 4)           # fp32 scale blocks
    t.check_cost(expect_flops=4.0 * b * c * h * lmax * d,
                 expect_bytes_read=reads)


def test_unregistered_op_conservative_default():
    main = fluid.Program()
    b = main.global_block().desc
    b.add_var(VarDesc("x", shape=[4, 4]))
    b.add_var(VarDesc("y", shape=[4, 4]))
    b.append_op(OpDesc("mystery_op", {"X": ["x"]}, {"Out": ["y"]}, {}))
    env = CostEnv(ProgramView(main.desc), 0)
    c = op_cost(env, b.ops[0])
    assert not c.registered
    assert c.flops == 16.0 and c.bytes_read == 64 and c.bytes_written == 64
    diag = main.analyze(level="cost", fetch_list=["y"])
    found = diag.by_code("unregistered-cost-rule")
    assert len(found) == 1 and "mystery_op" in found[0].message


def test_grad_rule_derived_from_base():
    """A *_grad op without its own rule prices at 2x the base rule's
    flops (vjp recompute) and counts as registered."""
    main = fluid.Program()
    b = main.global_block().desc
    b.add_var(VarDesc("x", shape=[4, 8]))
    b.add_var(VarDesc("w", shape=[8, 16]))
    b.add_var(VarDesc("out_g", shape=[4, 16]))
    b.add_var(VarDesc("x_g", shape=[4, 8]))
    b.append_op(OpDesc("mul_grad",
                       {"X": ["x"], "Y": ["w"], "Out@GRAD": ["out_g"]},
                       {"X@GRAD": ["x_g"]}, {}))
    env = CostEnv(ProgramView(main.desc), 0)
    c = op_cost(env, b.ops[0])
    assert c.registered
    assert c.flops == 2.0 * (2.0 * 4 * 16 * 8)


# ---------------------------------------------------------------------------
# peak-HBM planner: exact coordinates, aliasing, components
# ---------------------------------------------------------------------------

def _seeded_plan_program():
    """x(feed 512B) -> mul w(2048B persist) -> h(1024B) -> concat ->
    c(2048B) -> relu -> r (aliases c) -> reduce_sum -> out(4B).
    Hand-computed peak: 2048 + h + c = 5120 bytes at op#1."""
    main = fluid.Program()
    b = main.global_block().desc
    b.add_var(VarDesc("x", shape=[8, 16]))
    b.add_var(VarDesc("w", shape=[16, 32], persistable=True))
    b.add_var(VarDesc("h", shape=[8, 32]))
    b.add_var(VarDesc("c", shape=[8, 64]))
    b.add_var(VarDesc("r", shape=[8, 64]))
    b.add_var(VarDesc("out", shape=[1]))
    b.append_op(OpDesc("mul", {"X": ["x"], "Y": ["w"]}, {"Out": ["h"]},
                       {}))
    b.append_op(OpDesc("concat", {"X": ["h", "h"]}, {"Out": ["c"]},
                       {"axis": 1}))
    b.append_op(OpDesc("relu", {"X": ["c"]}, {"Out": ["r"]}, {}))
    b.append_op(OpDesc("reduce_sum", {"X": ["r"]}, {"Out": ["out"]}, {}))
    return main


def test_planner_peak_coordinates_exact():
    plan = plan_program(_seeded_plan_program())
    assert plan.peak_bytes == 5120
    assert (plan.peak_block, plan.peak_op) == (0, 1)
    assert plan.components == {"params": 2048, "kv_pool": 0,
                               "activations": 3072, "feeds": 0}
    # top contributor is the aliased c->r buffer (donation-aware reuse:
    # relu's output reuses concat's dying buffer, counted ONCE)
    top = plan.top(3)
    assert top[0]["var"] == "c→r" and top[0]["bytes"] == 2048
    assert {"var": "w", "bytes": 2048, "kind": "params",
            "live": None} in top
    # the byte timeline matches the hand walk
    bp = plan.blocks[0]
    assert bp.timeline == [1536, 3072, 2048, 2052]
    assert bp.peak_op == 1 and bp.peak_bytes == 3072


def test_planner_persistable_alias_is_free():
    """An output chained off a donated persistable (the cache_write /
    paged-pool idiom) shares the scope buffer — zero transient bytes."""
    main = fluid.Program()
    b = main.global_block().desc
    b.add_var(VarDesc("pool", shape=[4, 4], persistable=True))
    b.add_var(VarDesc("pool2", shape=[4, 4]))
    b.add_var(VarDesc("out", shape=[1]))
    b.append_op(OpDesc("relu", {"X": ["pool"]}, {"Out": ["pool2"]}, {}))
    b.append_op(OpDesc("reduce_sum", {"X": ["pool2"]},
                       {"Out": ["out"]}, {}))
    plan = plan_program(main)
    assert plan.peak_bytes == 64 + 4          # pool + out, pool2 free
    assert plan.components["activations"] == 4


def test_planner_kv_pool_component_and_sidecar():
    """The paged generator's pool AND its int8 fp32-scale sidecar land
    in the kv_pool component, matching kv_page_bytes * num_pages."""
    from paddle_tpu.serving.paged_decoder import (build_unified_program,
                                                  kv_page_bytes)
    from paddle_tpu.serving.decoder import _Cfg

    cfg = _Cfg(30, 30, 2, 2, 4, 4, 16, 32, 64)
    prog, _, _, _ = build_unified_program(
        cfg, src_len=8, max_out_len=8, page_size=4, num_pages=32,
        chunk_size=4, param_prefix="tk", kv_dtype="int8")
    plan = plan_program(prog, assume_batch=2)
    want = kv_page_bytes(2, 2, 4, 4, "int8") * 32
    assert plan.components["kv_pool"] == want
    assert plan.components["params"] > 0


def test_budget_finding_and_plint_exit(tmp_path, capsys):
    from paddle_tpu.tools import plint

    main = _seeded_plan_program()
    diag = main.analyze(level="cost", fetch_list=["out"],
                        options={"budget_bytes": 4096})
    over = diag.by_code("over-budget")
    assert len(over) == 1 and over[0].severity == "error"
    assert "params=2048" in over[0].message

    f = tmp_path / "prog.json"
    f.write_bytes(main.desc.serialize_to_string())
    assert plint.main([str(f), "--cost", "--budget", "4096",
                       "--fetch", "out"]) == 1
    capsys.readouterr()
    assert plint.main([str(f), "--cost", "--budget", "1000000",
                       "--fetch", "out"]) == 0
    capsys.readouterr()
    # --fail-on flips a warning-severity finding into exit 1
    b = main.global_block().desc
    b.add_var(VarDesc("m", shape=[1]))
    b.append_op(OpDesc("mystery_op", {"X": ["out"]}, {"Out": ["m"]}, {}))
    f.write_bytes(main.desc.serialize_to_string())
    assert plint.main([str(f), "--cost", "--fetch", "m"]) == 0
    capsys.readouterr()
    assert plint.main([str(f), "--cost", "--fetch", "m",
                       "--fail-on", "unregistered-cost-rule"]) == 1
    capsys.readouterr()


def test_book_program_cost_level_clean():
    """The mnist book program runs the whole cost family with zero
    errors and zero warnings — every op it uses has a cost rule."""
    from paddle_tpu.models import recognize_digits

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        img = fluid.layers.data("img", [1, 28, 28], "float32")
        label = fluid.layers.data("label", [1], "int64")
        _, avg_cost, acc = recognize_digits.conv_net(img, label)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(avg_cost)
    diag = main.analyze(level="cost", fetch_list=[avg_cost, acc],
                        options={"assume_batch": 64})
    assert not diag.has_errors, diag.render()
    assert not diag.warnings(), diag.render()
    rep = diag.reports["cost"]
    assert rep["memory"]["peak_bytes"] > rep["memory"]["components"][
        "params"]
    assert rep["roofline"]["total_flops"] > 1e8   # ~0.7 GFLOP at bs 64
    assert rep["roofline"]["step_time_s"] > 0


def test_roofline_chip_specs():
    spec = get_chip("v5e")
    assert spec.peak_flops == 197e12 and spec.hbm_bytes == 16 * 2 ** 30
    with pytest.raises(ValueError):
        get_chip("not-a-chip")
    main = _seeded_plan_program()
    fast = roofline(main, get_chip("v6e"))
    slow = roofline(main, get_chip("v2"))
    assert fast.step_time_s < slow.step_time_s
    assert fast.total_flops == slow.total_flops


# ---------------------------------------------------------------------------
# recompile-hazard lint + bucket enumeration
# ---------------------------------------------------------------------------

def test_recompile_value_shape_op_is_error():
    main = fluid.Program()
    b = main.global_block().desc
    for n in ("ids", "scores", "parents", "out_ids", "out_scores"):
        b.add_var(VarDesc(n, shape=[-1, 1]))
    b.append_op(OpDesc("beam_search_decode",
                       {"Ids": ["ids"], "Scores": ["scores"],
                        "ParentIdx": ["parents"]},
                       {"SentenceIds": ["out_ids"],
                        "SentenceScores": ["out_scores"]}, {}))
    diag = main.analyze(level="cost", fetch_list=["out_ids"])
    errs = diag.by_code("value-shape-op")
    assert len(errs) == 1 and errs[0].severity == "error"
    assert not diag.reports["recompile"]["closed"]


def test_recompile_dynamic_inner_dim_and_ragged():
    main = fluid.Program()
    b = main.global_block().desc
    b.add_var(VarDesc("x", shape=[-1, -1, 4]))
    b.add_var(VarDesc("s", shape=[-1, 1], lod_level=1))
    b.add_var(VarDesc("y", shape=[-1, 4]))
    b.append_op(OpDesc("reduce_sum", {"X": ["x"]}, {"Out": ["y"]},
                       {"dim": 1}))
    b.append_op(OpDesc("print", {"X": ["s"]}, {}, {}))
    diag = main.analyze(level="cost", fetch_list=["y"])
    assert diag.by_code("dynamic-inner-dim")
    assert diag.by_code("ragged-feed")


def test_bucket_enumeration_closed_product():
    from paddle_tpu.fluid.analysis.recompile import enumerate_buckets

    main = fluid.Program()
    b = main.global_block().desc
    b.add_var(VarDesc("x", shape=[-1, 8]))
    b.add_var(VarDesc("s", shape=[-1, 1], lod_level=1))
    b.add_var(VarDesc("y", shape=[-1, 8]))
    b.append_op(OpDesc("relu", {"X": ["x"]}, {"Out": ["y"]}, {}))
    b.append_op(OpDesc("print", {"X": ["s"]}, {}, {}))
    view = ProgramView(main.desc)
    buckets = enumerate_buckets(view, batch_buckets=(2, 4),
                                time_buckets=(8, 16))
    assert len(buckets) == 4
    assert all(e["closed"] for e in buckets)
    assert sorted({e["batch"] for e in buckets}) == [2, 4]
    # no declared buckets -> the axis is open
    open_set = enumerate_buckets(view)
    assert not all(e["closed"] for e in open_set)


def test_static_serving_program_single_bucket():
    """The paged decode-step program with a declared lane bucket is the
    zero-recompile steady state: exactly ONE closed signature."""
    from paddle_tpu.serving.paged_decoder import build_unified_program
    from paddle_tpu.serving.decoder import _Cfg

    prog, _, ids, _ = build_unified_program(
        _Cfg(30, 30, 2, 2, 4, 4, 16, 32, 64), src_len=8, max_out_len=8,
        page_size=4, num_pages=32, chunk_size=4, param_prefix="tb")
    diag = prog.analyze(level="cost", fetch_list=[ids],
                        options={"batch_buckets": (4,)})
    rep = diag.reports["recompile"]
    assert rep["closed"] and rep["bucket_count"] == 1
    assert rep["hazards"] == 0


def test_engine_bucket_set_and_static_estimate():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data(name="x", shape=[6], dtype="float32")
        y = fluid.layers.fc(input=x, size=4)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    from paddle_tpu.serving.engine import InferenceEngine

    eng = InferenceEngine(program=fluid.io.prune_program(main, [y]),
                          feed_names=["x"], fetch_vars=[y], scope=scope,
                          place=fluid.CPUPlace(),
                          batch_buckets=(2, 8))
    buckets = eng.bucket_set()
    assert len(buckets) == 2
    assert [e["batch"] for e in buckets] == [2, 8]
    assert all(e["closed"] for e in buckets)
    # estimate scales with the assumed batch, params stay constant
    small = eng.static_hbm_estimate(batch=2)
    big = eng.static_hbm_estimate(batch=256)
    assert big.peak_bytes > small.peak_bytes
    assert big.components["params"] == small.components["params"]


# ---------------------------------------------------------------------------
# comms estimator
# ---------------------------------------------------------------------------

def _sharded_net():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data("x", [64], "float32")
        y = fluid.layers.data("y", [1], "float32")
        h = fluid.layers.fc(input=x, size=128, act="relu")
        pred = fluid.layers.fc(
            input=h, size=1,
            param_attr=fluid.ParamAttr(name="w2",
                                       sharding=["mp", None]))
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, loss


def test_comms_partial_sum_and_grad_sync():
    main, loss = _sharded_net()
    diag = main.analyze(level="cost", fetch_list=[loss],
                        options={"assume_batch": 32,
                                 "mesh_axes": {"dp": 8, "mp": 4},
                                 "dcn_axes": ["dp"]})
    rep = diag.reports["comms"]
    kinds = {(c["axis"], c["kind"]) for c in rep["collectives"]}
    # w2 is sharded over its contracted dim -> mp partial-sum allreduce
    assert ("mp", "allreduce(partial-sum)") in kinds
    # every param's gradient syncs over the batch axis, once per param
    grad_syncs = [c for c in rep["collectives"]
                  if c["kind"] == "allreduce(grad-sync)"]
    assert len(grad_syncs) == 4        # w1, b1, w2, b2
    w1 = 64 * 128 * 4
    assert rep["grad_sync_bytes"] == w1 + 128 * 4 + 128 * 1 * 4 + 4
    # dp is declared DCN: ring wire bytes = 2*(n-1)/n * payload
    dp = rep["per_axis"]["dp"]
    assert dp["tier"] == "dcn"
    assert dp["wire_bytes"] == pytest.approx(
        2.0 * 7 / 8 * rep["grad_sync_bytes"])
    assert rep["dcn_bytes"] == pytest.approx(dp["wire_bytes"])
    # the EQuARX framing: int8 payload + 1/32-block fp32 scales
    assert rep["int8_quantized_dcn_bytes"] == pytest.approx(
        rep["dcn_bytes"] / 4.0 * (1 + 4.0 / 32.0))
    assert any(f.code == "dcn-bound" for f in diag.warnings())


def test_comms_silent_on_unsharded_program():
    main = _seeded_plan_program()
    diag = main.analyze(level="cost", fetch_list=["out"])
    assert not [f for f in diag.findings if f.pass_name == "comms"]
    assert diag.reports["comms"]["collectives"] == []


# ---------------------------------------------------------------------------
# executor preflight: counters keyed by level (satellite)
# ---------------------------------------------------------------------------

def test_preflight_counters_key_on_level():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data("x", [4], "float32")
        h = fluid.layers.fc(input=x, size=8)
        loss = fluid.layers.mean(h)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.zeros((2, 4), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss],
                validate="structural")
        # a cost run of the SAME program is a fresh analysis, not a
        # cache hit of the prior structural run
        exe.run(main, feed=feed, fetch_list=[loss], validate="cost")
        exe.run(main, feed=feed, fetch_list=[loss], validate="cost")
    st = exe.cache_stats()["validate"]
    assert st["runs"] == 2 and st["cached"] == 1
    assert st["by_level"]["structural"] == {"runs": 1, "cached": 0}
    assert st["by_level"]["cost"] == {"runs": 1, "cached": 1}


# ---------------------------------------------------------------------------
# memory_optimize: thin consumer of the byte timeline (satellite)
# ---------------------------------------------------------------------------

def test_memory_optimize_python_stats_carry_byte_timeline():
    from paddle_tpu.fluid.memory_optimization_transpiler import \
        _python_stats

    main = _seeded_plan_program()
    stats = _python_stats(main)
    # the native-compatible contract keys survive untouched
    for key in ("topo_order", "level", "live_range", "reuse_slot",
                "num_slots"):
        assert key in stats
    assert set(stats["live_range"]) == {"h", "c", "r", "out"}
    # plus the planner's byte view (one shared live-set derivation)
    assert stats["peak_transient_bytes"] == 3072
    assert stats["peak_op"] == 1
    assert stats["byte_timeline"] == [1536, 3072, 2048, 2052]


# ---------------------------------------------------------------------------
# serving wiring: registry static costing + scheduler budget
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def small_gen():
    from paddle_tpu.serving import PagedTransformerGenerator

    gen = PagedTransformerGenerator(
        30, 30, n_layer=2, n_head=2, d_key=4, d_value=4, d_model=16,
        d_inner_hid=32, max_length=64, src_len=8, max_out_len=8,
        page_size=4, chunk_size=4, num_pages=32, param_prefix="tcost",
        place=fluid.CPUPlace())
    gen.init_params(seed=7)
    return gen


def test_registry_costs_with_static_plan(tmp_path, small_gen):
    from paddle_tpu.serving.gateway import HBMBudgetError, ModelRegistry

    root = str(tmp_path)
    ModelRegistry.save_generator_artifact(small_gen, root, "m", "1")
    cfg = json.load(open(os.path.join(root, "m", "1",
                                      "gateway.json")))["config"]
    cost = ModelRegistry._estimate_cost(
        "generator", fluid.io.model_version_dir(root, "m", "1"), cfg)
    # the manifest-built desc and the live generator agree exactly.
    # An artifact load mounts a compiled/ AOT cache (ISSUE 14), so the
    # registry prices the no-donation dispatch its executables really
    # run; the live instance self-selects the same model once a cache
    # is mounted on its executor — compare like for like both ways.
    from paddle_tpu.fluid.compile_cache import CompileCache
    from paddle_tpu.serving.paged_decoder import estimate_generator_hbm

    plan = small_gen.static_hbm_estimate()       # no cache: donating
    assert plan.peak_bytes == \
        estimate_generator_hbm(cfg).peak_bytes
    assert cost == \
        estimate_generator_hbm(cfg, assume_donation=False).peak_bytes
    assert cost > plan.peak_bytes                # write-backs priced in
    small_gen.exe.set_compile_cache(
        CompileCache(os.path.join(root, "unused-cache")))
    try:
        assert cost == small_gen.static_hbm_estimate().peak_bytes
    finally:
        small_gen.exe.set_compile_cache(None)
    # …and the plan covers more than the old artifact-byte heuristic:
    # pool + activations, not just weight bytes on disk
    assert plan.components["kv_pool"] == \
        small_gen.page_bytes * small_gen.num_pages
    assert plan.components["activations"] > 0

    reg = ModelRegistry(root=root, hbm_budget_bytes=int(cost * 1.5))
    reg.load("m", "1")
    ModelRegistry.save_generator_artifact(small_gen, root, "m", "2")
    with pytest.raises(HBMBudgetError) as ei:
        reg.load("m", "2")
    # the refusal message carries the per-component breakdown
    msg = str(ei.value)
    assert "params=" in msg and "kv_pool=" in msg


def test_scheduler_budget_consults_static_estimate(small_gen):
    from paddle_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                              HBMBudgetError)

    plan = small_gen.static_hbm_estimate(assume_lanes=2)
    sched = ContinuousBatchingScheduler(
        hbm_budget_bytes=plan.peak_bytes + 64)
    sched.add_model("m@1", small_gen, 2)
    st = sched.stats()
    assert st["models"]["m@1"]["static_hbm_bytes"] == plan.peak_bytes
    assert st["hbm"]["committed_bytes"] == plan.peak_bytes
    assert not sched.can_admit_model(plan.peak_bytes)
    with pytest.raises(HBMBudgetError):
        sched.add_model("m@2", small_gen, 2)
