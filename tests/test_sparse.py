"""SelectedRows sparse embedding gradients (reference selected_rows.h:19,
lookup_table_op.cc grad, sgd_op.h / adagrad_op.cc SelectedRows kernels).

The contract: embedding(is_sparse=True) must train BIT-IDENTICALLY to the
dense path for sgd/adagrad (linear / per-row-quadratic updates), and
row-identically on touched rows for momentum/adam, whose sparse kernels
use the standard "lazy" semantics — untouched rows keep their moments
(dense momentum would decay every row every step; with zero-initialised
moments and a fixed touched set the two coincide exactly, which is what
the parametrised test below exercises).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import fluid
from paddle_tpu.fluid.core.selected_rows import SelectedRows, merge_rows


def test_merge_rows_sums_duplicates():
    rows = jnp.asarray([3, 1, 3, 7, 1], jnp.int32)
    vals = jnp.arange(10, dtype=jnp.float32).reshape(5, 2)
    merged = merge_rows(SelectedRows(rows, vals, height=10))
    dense = np.asarray(merged.to_dense())
    expect = np.zeros((10, 2), np.float32)
    for r, v in zip(np.asarray(rows), np.asarray(vals)):
        expect[r] += v
    np.testing.assert_allclose(dense, expect)
    # vacated slots carry the sentinel row
    assert (np.asarray(merged.rows) == 10).sum() == 2


def test_selected_rows_scatter_matches_dense():
    rows = jnp.asarray([0, 2, 2, 5], jnp.int32)
    vals = jnp.ones((4, 3), jnp.float32)
    sr = SelectedRows(rows, vals, height=6)
    base = jnp.zeros((6, 3), jnp.float32)
    np.testing.assert_allclose(np.asarray(sr.scatter_add_to(base)),
                               np.asarray(sr.to_dense()))


def _build_embedding_net(is_sparse, make_opt, vocab=50, dim=8):
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        ids = fluid.layers.data("ids", [6], "int64")
        emb = fluid.layers.embedding(ids, size=[vocab, dim],
                                     is_sparse=is_sparse,
                                     param_attr="emb_w")
        # second lookup on the SAME table -> grad fan-in `sum` op must
        # handle SelectedRows + SelectedRows
        emb2 = fluid.layers.embedding(ids, size=[vocab, dim],
                                      is_sparse=is_sparse,
                                      param_attr="emb_w")
        both = fluid.layers.elementwise_add(emb, emb2)
        pred = fluid.layers.fc(input=both, size=1, num_flatten_dims=2,
                               bias_attr=False)
        loss = fluid.layers.mean(pred)
        make_opt().minimize(loss)
    main.random_seed = startup.random_seed = 11
    return main, startup, scope, loss


OPTIMIZERS = [
    ("sgd", lambda: fluid.optimizer.SGD(learning_rate=0.1)),
    ("adagrad", lambda: fluid.optimizer.Adagrad(learning_rate=0.1)),
    ("momentum", lambda: fluid.optimizer.Momentum(learning_rate=0.1,
                                                  momentum=0.9)),
    ("adam", lambda: fluid.optimizer.Adam(learning_rate=0.1)),
]


@pytest.mark.parametrize("name,make_opt", OPTIMIZERS)
def test_sparse_matches_dense_training(name, make_opt):
    rng = np.random.RandomState(0)
    feed = {"ids": rng.randint(0, 50, (4, 6)).astype(np.int64)}
    got = {}
    for sp in (False, True):
        main, startup, scope, loss = _build_embedding_net(sp, make_opt)
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(4):
                exe.run(main, feed=feed, fetch_list=[loss])
            got[sp] = np.asarray(scope.find_var("emb_w"))
    np.testing.assert_allclose(got[True], got[False], atol=2e-7)
    # training actually moved the looked-up rows
    touched = np.unique(feed["ids"])
    assert np.abs(got[True][touched]).sum() > 0


def test_sparse_grad_is_selected_rows():
    """The lowered grad value really is a SelectedRows (no [V,D] dense
    buffer) — checked through the op emitters directly."""
    from paddle_tpu.fluid.core.registry import get_op_info, EmitCtx
    from paddle_tpu.fluid.core.desc import OpDesc

    w = jnp.zeros((1000, 4), jnp.float32)
    ids = jnp.asarray([[1], [7], [1]], jnp.int32)
    og = jnp.ones((3, 4), jnp.float32)
    op = OpDesc("lookup_table_grad",
                {"W": ["w"], "Ids": ["ids"], "Out@GRAD": ["og"]},
                {"W@GRAD": ["gw"]}, {"is_sparse": True})
    out = get_op_info("lookup_table_grad").emit(
        EmitCtx(op), {"W": [w], "Ids": [ids], "Out@GRAD": [og]})
    g = out["W@GRAD"][0]
    assert isinstance(g, SelectedRows)
    assert g.values.shape == (3, 4) and g.height == 1000
    np.testing.assert_array_equal(np.asarray(g.rows), [1, 7, 1])


def test_padding_idx_rows_get_no_grad():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        ids = fluid.layers.data("ids", [4], "int64")
        emb = fluid.layers.embedding(ids, size=[20, 4], is_sparse=True,
                                     padding_idx=0, param_attr="emb_w")
        loss = fluid.layers.mean(emb)
        fluid.optimizer.SGD(learning_rate=1.0).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {"ids": np.array([[0, 1, 2, 0]], np.int64)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        before = np.asarray(scope.find_var("emb_w")).copy()
        exe.run(main, feed=feed, fetch_list=[loss])
        after = np.asarray(scope.find_var("emb_w"))
    np.testing.assert_array_equal(after[0], before[0])   # pad row untouched
    assert np.abs(after[1] - before[1]).max() > 0        # real row updated


def test_ctr_wide_and_deep_trains():
    """BASELINE config #5: wide&deep CTR with sparse embeddings converges
    on a synthetic click signal."""
    from paddle_tpu.models import ctr

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    n_slots, vocab, batch = 6, 1000, 32
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        sparse_ids = [fluid.layers.data(f"C{i}", [1], "int64")
                      for i in range(n_slots)]
        dense = fluid.layers.data("dense", [5], "float32")
        label = fluid.layers.data("label", [1], "float32")
        avg_cost, prob = ctr.wide_and_deep(
            sparse_ids, dense, label, slot_vocab=vocab, embed_dim=8,
            hidden_sizes=(32, 16))
        fluid.optimizer.Adagrad(learning_rate=0.1).minimize(avg_cost)
    main.random_seed = startup.random_seed = 7

    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (batch, n_slots)).astype(np.int64)
    dense_v = rng.randn(batch, 5).astype(np.float32)
    # click iff slot-0 id is even (learnable from the wide part)
    label_v = (ids[:, 0] % 2 == 0).astype(np.float32)[:, None]
    feed = {f"C{i}": ids[:, i:i + 1] for i in range(n_slots)}
    feed["dense"] = dense_v
    feed["label"] = label_v

    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(30):
            l, = exe.run(main, feed=feed, fetch_list=[avg_cost])
            losses.append(float(l))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_sparse_embedding_data_parallel():
    """The pserver->ICI path of BASELINE config #5: sparse-grad training
    under a dp mesh matches single-device training exactly."""
    from paddle_tpu import parallel

    rng = np.random.RandomState(0)
    feed = {"ids": rng.randint(0, 50, (8, 6)).astype(np.int64)}
    got = {}
    for use_mesh in (False, True):
        main, startup, scope, loss = _build_embedding_net(
            True, lambda: fluid.optimizer.SGD(learning_rate=0.1))
        exe = fluid.Executor(fluid.CPUPlace())
        import contextlib
        ctx = parallel.mesh_guard(parallel.make_mesh({"dp": 4})) \
            if use_mesh else contextlib.nullcontext()
        with ctx, fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(3):
                exe.run(main, feed=feed, fetch_list=[loss])
            got[use_mesh] = np.asarray(scope.find_var("emb_w"))
    np.testing.assert_allclose(got[True], got[False], atol=1e-6)


def test_sparse_grad_regularizer_and_clip():
    """Regularization on a sparse-grad param warns + skips; gradient clip
    raises a clear error (r2 review finding: both used to crash at trace
    time inside elementwise emitters)."""
    import warnings
    from paddle_tpu.fluid.regularizer import L2Decay

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        ids = fluid.layers.data("ids", [4], "int64")
        emb = fluid.layers.embedding(ids, size=[20, 4], is_sparse=True,
                                     param_attr="emb_w")
        loss = fluid.layers.mean(emb)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            fluid.optimizer.SGD(learning_rate=0.1,
                                regularization=L2Decay(1e-4)).minimize(loss)
        assert any("sparse-grad" in str(x.message) for x in w)
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {"ids": np.array([[1, 2, 3, 4]], np.int64)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        l, = exe.run(main, feed=feed, fetch_list=[loss])
    assert np.isfinite(float(l))

    # clip raises a clear error instead of a trace-time crash
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2), fluid.unique_name.guard():
        ids = fluid.layers.data("ids", [4], "int64")
        emb = fluid.layers.embedding(ids, size=[20, 4], is_sparse=True)
        loss = fluid.layers.mean(emb)
        pg = fluid.backward.append_backward(loss)
        for p, _ in pg:
            p.gradient_clip_attr = fluid.clip.GradientClipByValue(1.0)
        with pytest.raises(NotImplementedError, match="sparse-grad"):
            fluid.clip.append_gradient_clip_ops(pg)


class TestSparseApplyMomentumAdam:
    """r3 (VERDICT r2 missing/weak #5,#8): momentum and adam apply
    SelectedRows grads with row-sparse moment updates — no densify."""

    def _sr(self, vocab=1000, dim=4):
        rows = jnp.asarray([1, 7, 1], jnp.int32)     # duplicate row 1
        vals = jnp.asarray([[1.0] * dim, [2.0] * dim, [0.5] * dim],
                           jnp.float32)
        return SelectedRows(rows, vals, vocab)

    def _emit(self, op_type, ins, attrs):
        from paddle_tpu.fluid.core.desc import OpDesc
        from paddle_tpu.fluid.core.registry import EmitCtx, get_op_info

        op = OpDesc(op_type, {k: [k] for k in ins},
                    {}, dict(attrs))
        return get_op_info(op_type).emit(EmitCtx(op),
                                         {k: [v] for k, v in ins.items()})

    def test_momentum_sparse_no_densify(self, monkeypatch):
        monkeypatch.setattr(
            SelectedRows, "to_dense",
            lambda self: (_ for _ in ()).throw(
                AssertionError("momentum densified a SelectedRows grad")))
        g = self._sr()
        p = jnp.zeros((1000, 4), jnp.float32)
        v = jnp.zeros((1000, 4), jnp.float32)
        lr = jnp.asarray([0.1], jnp.float32)
        out = self._emit("momentum",
                         {"Param": p, "Grad": g, "Velocity": v,
                          "LearningRate": lr}, {"mu": 0.9})
        po = np.asarray(out["ParamOut"][0])
        vo = np.asarray(out["VelocityOut"][0])
        # row 1 saw summed duplicate grad 1.5; row 7 grad 2.0
        np.testing.assert_allclose(vo[1], 1.5)
        np.testing.assert_allclose(vo[7], 2.0)
        np.testing.assert_allclose(po[1], -0.15, atol=1e-7)
        np.testing.assert_allclose(po[7], -0.2, atol=1e-7)
        assert np.abs(po[0]).max() == 0 and np.abs(vo[0]).max() == 0

    def test_adam_sparse_no_densify_matches_dense_rows(self, monkeypatch):
        monkeypatch.setattr(
            SelectedRows, "to_dense",
            lambda self: (_ for _ in ()).throw(
                AssertionError("adam densified a SelectedRows grad")))
        g = self._sr()
        p = jnp.ones((1000, 4), jnp.float32)
        m1 = jnp.zeros((1000, 4), jnp.float32)
        m2 = jnp.zeros((1000, 4), jnp.float32)
        lr = jnp.asarray([0.1], jnp.float32)
        b1p = jnp.asarray([0.9], jnp.float32)
        b2p = jnp.asarray([0.999], jnp.float32)
        out = self._emit("adam",
                         {"Param": p, "Grad": g, "LearningRate": lr,
                          "Moment1": m1, "Moment2": m2, "Beta1Pow": b1p,
                          "Beta2Pow": b2p},
                         {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8})
        po = np.asarray(out["ParamOut"][0])
        # dense-equivalent math on touched rows (duplicates pre-summed)
        for row, gr in [(1, 1.5), (7, 2.0)]:
            m1n = 0.1 * gr
            m2n = 0.001 * gr * gr
            lr_t = 0.1 * np.sqrt(1 - 0.999) / (1 - 0.9)
            want = 1.0 - lr_t * m1n / (np.sqrt(m2n) + 1e-8)
            np.testing.assert_allclose(po[row], want, rtol=1e-6)
        np.testing.assert_allclose(po[0], 1.0)       # untouched row
        # beta powers advance globally
        np.testing.assert_allclose(np.asarray(out["Beta1PowOut"][0]),
                                   0.81, rtol=1e-6)

    def test_ctr_adam_end_to_end_sparse(self, monkeypatch):
        """CTR-style net under Adam trains with is_sparse=True and never
        materialises a dense [V, D] grad (VERDICT r2 ask)."""
        monkeypatch.setattr(
            SelectedRows, "to_dense",
            lambda self: (_ for _ in ()).throw(
                AssertionError("sparse path densified under Adam")))
        main, startup, scope, loss = _build_embedding_net(
            True, lambda: fluid.optimizer.Adam(learning_rate=0.05))
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(3)
        feed = {"ids": rng.randint(0, 50, (4, 6)).astype(np.int64)}
        with fluid.scope_guard(scope):
            exe.run(startup)
            losses = [float(np.asarray(
                exe.run(main, feed=feed, fetch_list=[loss])[0]))
                for _ in range(6)]
        assert losses[-1] < losses[0]
