"""Flash attention + ring attention vs a naive reference.

Mirrors the reference's Compare2Function CPU-vs-GPU pattern
(paddle/function/FunctionTest.h): the naive full-matrix softmax attention is
the golden; the blocked/ring implementations must match in forward and grad.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels import (flash_attention, ring_attention,
                                ring_attention_sharded)
from paddle_tpu.parallel import make_mesh


def naive_attention(q, k, v, bias=None, causal=False, sm_scale=None):
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        lq, lk = s.shape[-2:]
        mask = jnp.arange(lq)[:, None] >= jnp.arange(lk)[None, :]
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def make_qkv(b=2, h=3, lq=64, lk=64, d=16, seed=0):
    r = np.random.RandomState(seed)
    q = r.randn(b, h, lq, d).astype(np.float32)
    k = r.randn(b, h, lk, d).astype(np.float32)
    v = r.randn(b, h, lk, d).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_xla_matches_naive(causal):
    q, k, v = make_qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                          impl="xla")
    ref = naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_xla_bias():
    q, k, v = make_qkv()
    r = np.random.RandomState(1)
    bias = jnp.asarray(r.randn(2, 1, 64, 64).astype(np.float32))
    out = flash_attention(q, k, v, bias=bias, block_q=16, block_k=16,
                          impl="xla")
    ref = naive_attention(q, k, v, bias=bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grad_matches_naive(causal):
    q, k, v = make_qkv(lq=32, lk=32)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=causal, block_q=8, block_k=8,
                               impl="xla").sum()

    def loss_naive(q, k, v):
        return naive_attention(q, k, v, causal=causal).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_flash_bias_grad():
    q, k, v = make_qkv(lq=32, lk=32)
    bias = jnp.asarray(np.random.RandomState(1).randn(1, 3, 32, 32)
                       .astype(np.float32))
    g1 = jax.grad(lambda b: flash_attention(
        q, k, v, bias=b, block_q=8, block_k=8, impl="xla").sum())(bias)
    g2 = jax.grad(lambda b: naive_attention(q, k, v, bias=b).sum())(bias)
    np.testing.assert_allclose(g1, g2, atol=5e-4, rtol=5e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_pallas_interpret_matches_naive(causal):
    # pallas kernel semantics validated in interpreter mode on CPU — the
    # same kernel compiles for real on TPU (impl='pallas')
    q, k, v = make_qkv(b=1, h=2, lq=32, lk=32, d=8)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                          impl="pallas_interpret")
    ref = naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_pallas_interpret_bias():
    q, k, v = make_qkv(b=2, h=2, lq=32, lk=32, d=8)
    bias = jnp.asarray(np.random.RandomState(1).randn(2, 1, 32, 32)
                       .astype(np.float32))
    out = flash_attention(q, k, v, bias=bias, block_q=16, block_k=16,
                          impl="pallas_interpret")
    ref = naive_attention(q, k, v, bias=bias)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------------
# ring attention on the virtual 8-device mesh
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_naive(causal):
    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    q, k, v = make_qkv(b=2, h=2, lq=32, lk=32, d=8)
    out = ring_attention_sharded(mesh, q, k, v, causal=causal,
                                 dp_axis=None)
    ref = naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


def test_ring_attention_bias():
    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    q, k, v = make_qkv(b=2, h=2, lq=32, lk=32, d=8)
    # padding-style bias: rows local-shardable, columns global
    bias = np.zeros((2, 1, 32, 32), np.float32)
    bias[:, :, :, 28:] = -1e9
    bias = jnp.asarray(bias)
    out = ring_attention_sharded(mesh, q, k, v, bias=bias, dp_axis=None)
    ref = naive_attention(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


def test_ring_attention_grad():
    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    q, k, v = make_qkv(b=1, h=2, lq=32, lk=32, d=8)

    def loss_ring(q, k, v):
        return ring_attention_sharded(mesh, q, k, v, causal=True,
                                      dp_axis=None).sum()

    def loss_naive(q, k, v):
        return naive_attention(q, k, v, causal=True).sum()

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_ring_attention_dp_sp_mesh():
    # combined data parallel x sequence parallel
    mesh = make_mesh({"dp": 2, "sp": 4}, jax.devices()[:8])
    q, k, v = make_qkv(b=2, h=2, lq=32, lk=32, d=8)
    out = ring_attention_sharded(mesh, q, k, v, causal=True)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


def test_flash_non_divisible_lengths():
    # lengths not a multiple of the block: entry pads + masks (regression:
    # the xla path used to silently truncate tail keys)
    q, k, v = make_qkv(lq=48, lk=48, d=8)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                          impl="xla")
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    g1 = jax.grad(lambda q: flash_attention(
        q, k, v, causal=True, block_q=32, block_k=32, impl="xla").sum())(q)
    g2 = jax.grad(lambda q: naive_attention(q, k, v, causal=True).sum())(q)
    np.testing.assert_allclose(g1, g2, atol=5e-4, rtol=5e-4)


def test_flash_non_divisible_bias_grad():
    q, k, v = make_qkv(lq=48, lk=48, d=8)
    bias = jnp.asarray(np.random.RandomState(1).randn(2, 1, 48, 48)
                       .astype(np.float32))
    g1 = jax.grad(lambda b: flash_attention(
        q, k, v, bias=b, block_q=32, block_k=32, impl="xla").sum())(bias)
    g2 = jax.grad(lambda b: naive_attention(q, k, v, bias=b).sum())(bias)
    np.testing.assert_allclose(g1, g2, atol=5e-4, rtol=5e-4)


# ---------------------------------------------------------------------------
# in-kernel attention-probability dropout (VERDICT r1 weak#4)
# ---------------------------------------------------------------------------

def naive_dropout_attention(q, k, v, seed, rate, bias=None, causal=False):
    """Golden: dense softmax attention with the SAME hash mask the kernels
    use, applied to the normalised probabilities (inverted dropout)."""
    from paddle_tpu.kernels.flash_attention import keep_scale
    b, h, lq, _ = q.shape
    lk = k.shape[2]
    sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        mask = jnp.arange(lq)[:, None] >= jnp.arange(lk)[None, :]
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    bh = (jnp.arange(b, dtype=jnp.int32)[:, None] * h +
          jnp.arange(h, dtype=jnp.int32)[None, :])[:, :, None, None]
    rows = jnp.arange(lq, dtype=jnp.int32)[None, None, :, None]
    cols = jnp.arange(lk, dtype=jnp.int32)[None, None, None, :]
    scale = keep_scale(jnp.uint32(seed), bh, rows, cols, rate)
    return jnp.einsum("bhqk,bhkd->bhqd", p * scale,
                      v.astype(jnp.float32)).astype(q.dtype)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_dropout_matches_hash_reference(causal):
    q, k, v = make_qkv(lq=32, lk=32, d=8)
    out = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8,
                          impl="xla", dropout_rate=0.3, dropout_seed=7)
    ref = naive_dropout_attention(q, k, v, seed=7, rate=0.3, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    # and the grads: fwd custom-vjp vs jax AD through the dense reference
    g1 = jax.grad(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, block_q=8, block_k=8, impl="xla",
        dropout_rate=0.3, dropout_seed=7).sum(), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: naive_dropout_attention(
        q, k, v, seed=7, rate=0.3, causal=causal).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_flash_dropout_bias_grad():
    q, k, v = make_qkv(lq=32, lk=32, d=8)
    bias = jnp.asarray(np.random.RandomState(1).randn(2, 1, 32, 32)
                       .astype(np.float32))
    g1 = jax.grad(lambda b: flash_attention(
        q, k, v, bias=b, block_q=8, block_k=8, impl="xla",
        dropout_rate=0.2, dropout_seed=3).sum())(bias)
    g2 = jax.grad(lambda b: naive_dropout_attention(
        q, k, v, seed=3, rate=0.2, bias=b).sum())(bias)
    np.testing.assert_allclose(g1, g2, atol=5e-4, rtol=5e-4)


def test_flash_dropout_pallas_interpret_matches_xla():
    # the pallas kernel's in-kernel hash mask must equal the XLA path's —
    # that is what makes the custom-vjp backward consistent on TPU
    q, k, v = make_qkv(b=1, h=2, lq=32, lk=32, d=8)
    out_p = flash_attention(q, k, v, block_q=16, block_k=16,
                            impl="pallas_interpret",
                            dropout_rate=0.25, dropout_seed=11)
    out_x = flash_attention(q, k, v, block_q=16, block_k=16, impl="xla",
                            dropout_rate=0.25, dropout_seed=11)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x),
                               atol=2e-5, rtol=2e-5)


def test_flash_dropout_statistics():
    # ~rate of the attention mass is dropped; mean is preserved (inverted)
    q, k, v = make_qkv(b=4, h=4, lq=64, lk=64, d=8)
    clean = flash_attention(q, k, v, impl="xla")
    drop = flash_attention(q, k, v, impl="xla", dropout_rate=0.5,
                           dropout_seed=123)
    assert not np.allclose(np.asarray(clean), np.asarray(drop))
    # different seeds give different masks; same seed reproduces
    drop2 = flash_attention(q, k, v, impl="xla", dropout_rate=0.5,
                            dropout_seed=124)
    drop_same = flash_attention(q, k, v, impl="xla", dropout_rate=0.5,
                                dropout_seed=123)
    assert not np.allclose(np.asarray(drop), np.asarray(drop2))
    np.testing.assert_array_equal(np.asarray(drop), np.asarray(drop_same))


def test_keep_scale_rate():
    from paddle_tpu.kernels.flash_attention import keep_scale
    rows = jnp.arange(512, dtype=jnp.int32)[:, None]
    cols = jnp.arange(512, dtype=jnp.int32)[None, :]
    sc = keep_scale(jnp.uint32(42), jnp.int32(0), rows, cols, 0.3)
    frac_dropped = float((sc == 0).mean())
    assert abs(frac_dropped - 0.3) < 0.01


def test_ring_dropout_runs_and_differs():
    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    q, k, v = make_qkv(b=2, h=2, lq=32, lk=32, d=8)
    clean = ring_attention_sharded(mesh, q, k, v, dp_axis=None)
    drop = ring_attention_sharded(mesh, q, k, v, dp_axis=None,
                                  dropout_rate=0.4, dropout_seed=5)
    assert not np.allclose(np.asarray(clean), np.asarray(drop))
    # deterministic given the seed, and differentiable
    drop2 = ring_attention_sharded(mesh, q, k, v, dp_axis=None,
                                   dropout_rate=0.4, dropout_seed=5)
    np.testing.assert_array_equal(np.asarray(drop), np.asarray(drop2))
    g = jax.grad(lambda q: ring_attention_sharded(
        mesh, q, k, v, dp_axis=None, dropout_rate=0.4,
        dropout_seed=5).sum())(q)
    assert np.isfinite(np.asarray(g)).all()


# ---------------------------------------------------------------------------
# Pallas backward kernels + layouts (r4: bwd moved from XLA scan to Pallas)
# ---------------------------------------------------------------------------

@pytest.fixture
def pallas_bwd(monkeypatch):
    """Route even tiny shapes through the dq/dkv Pallas kernels (production
    keeps the XLA-scan backward below PALLAS_BWD_MIN_L)."""
    import importlib
    mod = importlib.import_module("paddle_tpu.kernels.flash_attention")
    monkeypatch.setattr(mod, "PALLAS_BWD_MIN_L", 0)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_pallas_grad_matches_naive(causal, pallas_bwd):
    # bias-free grads route through the dq/dkv Pallas kernels
    q, k, v = make_qkv(b=1, h=2, lq=32, lk=32, d=8)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=causal, block_q=16,
                               block_k=16, impl="pallas_interpret").sum()

    def loss_naive(q, k, v):
        return naive_attention(q, k, v, causal=causal).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_flash_pallas_grad_weighted_cotangent(pallas_bwd):
    # non-uniform do exercises delta = rowsum(o*do) properly
    q, k, v = make_qkv(b=1, h=2, lq=32, lk=32, d=8)
    w = jnp.asarray(np.random.RandomState(5).randn(1, 2, 32, 8)
                    .astype(np.float32))

    def loss(fn):
        def f(q, k, v):
            return (fn(q, k, v) * w).sum()
        return f

    flash = loss(lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=8, block_k=8,
        impl="pallas_interpret"))
    naive = loss(lambda q, k, v: naive_attention(q, k, v, causal=True))
    g1 = jax.grad(flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_flash_pallas_dropout_grad_matches_xla(pallas_bwd):
    # in-kernel hash dropout: pallas bwd mask must equal the XLA path's
    q, k, v = make_qkv(b=1, h=2, lq=32, lk=32, d=8)

    def g(impl):
        return jax.grad(lambda q, k, v: flash_attention(
            q, k, v, block_q=16, block_k=16, impl=impl,
            dropout_rate=0.25, dropout_seed=11).sum(),
            argnums=(0, 1, 2))(q, k, v)

    for a, b in zip(g("pallas_interpret"), g("xla")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_flash_pallas_non_divisible_kv_len(pallas_bwd):
    # padding is masked by the static kv_len bound inside the kernels (no
    # synthetic bias tensor) — fwd and grad
    q, k, v = make_qkv(b=1, h=2, lq=40, lk=40, d=8)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                          impl="pallas_interpret")
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    g1 = jax.grad(lambda q, k, v: flash_attention(
        q, k, v, causal=True, block_q=32, block_k=32,
        impl="pallas_interpret").sum(), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: naive_attention(
        q, k, v, causal=True).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


@pytest.mark.parametrize("impl", ["xla", "pallas_interpret"])
def test_flash_blhd_layout_matches_bhld(impl, pallas_bwd):
    # layout='blhd' takes [b, l, h, d] directly — no split-heads transposes
    q, k, v = make_qkv(b=2, h=2, lq=32, lk=32, d=8)
    qt = jnp.transpose(q, (0, 2, 1, 3))        # -> [b, l, h, d]
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    out = flash_attention(qt, kt, vt, causal=True, block_q=16, block_k=16,
                          impl=impl, layout="blhd")
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(jnp.transpose(out, (0, 2, 1, 3)), ref,
                               atol=2e-5, rtol=2e-5)

    g1 = jax.grad(lambda x: flash_attention(
        x, kt, vt, causal=True, block_q=16, block_k=16, impl=impl,
        layout="blhd").sum())(qt)
    g2 = jax.grad(lambda x: naive_attention(x, k, v, causal=True).sum())(q)
    np.testing.assert_allclose(jnp.transpose(g1, (0, 2, 1, 3)), g2,
                               atol=5e-4, rtol=5e-4)


def test_flash_pallas_rect_blocks_and_lengths(pallas_bwd):
    # lq != lk and block_q != block_k through the pallas kernels
    q, k, v = make_qkv(b=1, h=2, lq=32, lk=64, d=8)
    out = flash_attention(q, k, v, block_q=16, block_k=32,
                          impl="pallas_interpret")
    ref = naive_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    g1 = jax.grad(lambda k: flash_attention(
        q, k, v, block_q=16, block_k=32,
        impl="pallas_interpret").sum())(k)
    g2 = jax.grad(lambda k: naive_attention(q, k, v).sum())(k)
    np.testing.assert_allclose(g1, g2, atol=5e-4, rtol=5e-4)


@pytest.mark.parametrize("impl", ["xla", "pallas_interpret"])
def test_flash_block_offsets(impl, pallas_bwd):
    """block_offsets place the q/k blocks at global positions: the causal
    mask and the dropout hash must behave as if the blocks were slices of
    one long sequence (the contract ring attention relies on)."""
    full_q, full_k, full_v = make_qkv(b=1, h=2, lq=64, lk=64, d=8)
    ro, co = 32, 16            # q block = rows 32..63, k block = cols 16..47
    q = full_q[:, :, 32:64]
    k = full_k[:, :, 16:48]
    v = full_v[:, :, 16:48]

    # causal: out == the corresponding tile of the full causal attention
    # restricted to these keys
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (8 ** -0.5)
    rows = (ro + jnp.arange(32))[:, None]
    cols = (co + jnp.arange(32))[None, :]
    s = jnp.where(rows >= cols, s, -1e30)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1),
                     v.astype(jnp.float32))
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16,
                          impl=impl, block_offsets=(ro, co))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    # grads flow and match the dense reference
    g1 = jax.grad(lambda k_: flash_attention(
        q, k_, v, causal=True, block_q=16, block_k=16, impl=impl,
        block_offsets=(ro, co)).sum())(k)

    def dense(k_):
        s_ = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k_.astype(jnp.float32)) * (8 ** -0.5)
        s_ = jnp.where(rows >= cols, s_, -1e30)
        return jnp.einsum("bhqk,bhkd->bhqd",
                          jax.nn.softmax(s_, axis=-1),
                          v.astype(jnp.float32)).sum()

    g2 = jax.grad(dense)(k)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               atol=5e-4, rtol=5e-4)

    # dropout hash keys on GLOBAL positions: the offset call equals the
    # corresponding slice semantics of the hash mask
    outd = flash_attention(q, k, v, block_q=16, block_k=16, impl=impl,
                           dropout_rate=0.3, dropout_seed=5,
                           block_offsets=(ro, co))
    refd = naive_dropout_attention_tile(q, k, v, seed=5, rate=0.3,
                                        row_off=ro, col_off=co)
    np.testing.assert_allclose(np.asarray(outd), np.asarray(refd),
                               atol=2e-5, rtol=2e-5)


def naive_dropout_attention_tile(q, k, v, seed, rate, row_off, col_off):
    from paddle_tpu.kernels.flash_attention import keep_scale
    b, h, lq, _ = q.shape
    lk = k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (q.shape[-1] ** -0.5)
    p = jax.nn.softmax(s, axis=-1)
    bh = (jnp.arange(b, dtype=jnp.int32)[:, None] * h +
          jnp.arange(h, dtype=jnp.int32)[None, :])[:, :, None, None]
    rows = (row_off + jnp.arange(lq, dtype=jnp.int32))[None, None, :, None]
    cols = (col_off + jnp.arange(lk, dtype=jnp.int32))[None, None, None, :]
    scale = keep_scale(jnp.uint32(seed), bh, rows, cols, rate)
    return jnp.einsum("bhqk,bhkd->bhqd", p * scale, v.astype(jnp.float32))


def test_ring_flash_chunks_match_unsharded_flash():
    """The r4 ring (flash kernels per held block, offset masks, lse merge)
    must equal the UNSHARDED flash kernel bit-for-bit in semantics — same
    causal mask, same global-position dropout hash — for both values and
    gradients."""
    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    q, k, v = make_qkv(b=2, h=2, lq=64, lk=64, d=8, seed=3)

    ring = ring_attention_sharded(mesh, q, k, v, causal=True, dp_axis=None,
                                  dropout_rate=0.25, dropout_seed=42)
    flat = flash_attention(q, k, v, causal=True, impl="xla",
                           dropout_rate=0.25, dropout_seed=42)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(flat),
                               atol=2e-5, rtol=2e-5)

    g_ring = jax.grad(lambda v_: ring_attention_sharded(
        mesh, q, k, v_, causal=True, dp_axis=None, dropout_rate=0.25,
        dropout_seed=42).sum())(v)
    g_flat = jax.grad(lambda v_: flash_attention(
        q, k, v_, causal=True, impl="xla", dropout_rate=0.25,
        dropout_seed=42).sum())(v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_flat),
                               atol=5e-4, rtol=5e-4)


def test_ring_non_divisible_shards():
    """Local shards that don't divide the kernel blocks pad + mask inside
    the ring (kv_len on local columns, offsets on global ones)."""
    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    q, k, v = make_qkv(b=1, h=2, lq=24, lk=24, d=8, seed=6)  # shards of 6
    out = ring_attention_sharded(mesh, q, k, v, causal=True, dp_axis=None)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)
    g1 = jax.grad(lambda q_: ring_attention_sharded(
        mesh, q_, k, v, causal=True, dp_axis=None).sum())(q)
    g2 = jax.grad(lambda q_: naive_attention(q_, k, v, causal=True).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               atol=5e-4, rtol=5e-4)


@pytest.mark.parametrize("impl", ["xla", "pallas_interpret"])
def test_flash_dead_rows_zero_output(impl):
    """Dead-row contract (r4 ADVICE): causal + block_offsets placing the
    whole k/v block strictly after the queries means every row has zero
    live keys — both impls must return output 0 and lse +inf (observable
    here as exactly-zero output and zero gradient), not uniform-attention
    garbage over masked keys."""
    q, k, v = make_qkv(b=1, h=2, lq=16, lk=16, d=8, seed=9)
    out = flash_attention(q, k, v, causal=True, impl=impl, block_q=8,
                          block_k=8, block_offsets=(0, 16))
    np.testing.assert_array_equal(np.asarray(out), 0.0)
    g = jax.grad(lambda v_: flash_attention(
        q, k, v_, causal=True, impl=impl, block_q=8, block_k=8,
        block_offsets=(0, 16)).sum())(v)
    assert np.all(np.isfinite(np.asarray(g)))
    np.testing.assert_array_equal(np.asarray(g), 0.0)

    # mixed: kv block straddles the diagonal — live rows still match the
    # naive softmax over their visible keys, dead rows are zero
    out2 = flash_attention(q, k, v, causal=True, impl=impl, block_q=8,
                           block_k=8, block_offsets=(0, 8))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (q.shape[-1] ** -0.5)
    rows = jnp.arange(16)[:, None]; cols = 8 + jnp.arange(16)[None, :]
    sm = jnp.where(rows >= cols, s, -jnp.inf)
    ref = jnp.einsum("bhqk,bhkd->bhqd",
                     jnp.where(rows[None, None] >= 8,
                               jax.nn.softmax(sm, axis=-1), 0.0), v)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------------
# Ulysses all-to-all sequence parallelism on the virtual mesh
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_naive(causal):
    from paddle_tpu.kernels import ulysses_attention_sharded

    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    q, k, v = make_qkv(b=2, h=4, lq=32, lk=32, d=8)
    out = ulysses_attention_sharded(mesh, q, k, v, causal=causal,
                                    dp_axis=None)
    ref = naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


def test_ulysses_attention_bias():
    from paddle_tpu.kernels import ulysses_attention_sharded

    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    q, k, v = make_qkv(b=2, h=4, lq=32, lk=32, d=8)
    bias = np.zeros((2, 1, 32, 32), np.float32)
    bias[:, :, :, 28:] = -1e9       # padding mask, columns global
    bias = jnp.asarray(bias)
    out = ulysses_attention_sharded(mesh, q, k, v, bias=bias,
                                    dp_axis=None)
    ref = naive_attention(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


def test_ulysses_attention_grad():
    from paddle_tpu.kernels import ulysses_attention_sharded

    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    q, k, v = make_qkv(b=1, h=4, lq=32, lk=32, d=8)

    def loss_uly(q, k, v):
        return ulysses_attention_sharded(mesh, q, k, v, causal=True,
                                         dp_axis=None).sum()

    def loss_naive(q, k, v):
        return naive_attention(q, k, v, causal=True).sum()

    g1 = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), b, atol=5e-4, rtol=5e-4)


def test_ulysses_dp_sp_mesh():
    """Combined dp x sp mesh: batch and sequence sharded together."""
    from paddle_tpu.kernels import ulysses_attention_sharded

    mesh = make_mesh({"dp": 2, "sp": 2}, jax.devices()[:4])
    q, k, v = make_qkv(b=4, h=2, lq=32, lk=32, d=8)
    out = ulysses_attention_sharded(mesh, q, k, v, causal=True)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


def test_ulysses_rejects_non_divisible_heads():
    from paddle_tpu.kernels import ulysses_attention_sharded

    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    q, k, v = make_qkv(b=1, h=3, lq=32, lk=32, d=8)
    with pytest.raises(ValueError, match="head count"):
        ulysses_attention_sharded(mesh, q, k, v)


def test_fused_attention_op_ulysses_matches_single(fresh_programs):
    """The fused_attention op routes sp_impl='ulysses' under an sp mesh
    and matches the meshless run."""
    from paddle_tpu import fluid, parallel

    main, startup, scope = fresh_programs
    q = fluid.layers.data("q", [4, 32, 8], "float32")
    k = fluid.layers.data("k", [4, 32, 8], "float32")
    v = fluid.layers.data("v", [4, 32, 8], "float32")
    out = fluid.layers.fused_attention(q, k, v, causal=True,
                                       seq_parallel=True,
                                       sp_impl="ulysses")
    qv, kv, vv = make_qkv(b=2, h=4, lq=32, lk=32, d=8)
    feed = {"q": np.asarray(qv), "k": np.asarray(kv), "v": np.asarray(vv)}
    exe = fluid.Executor(fluid.CPUPlace())
    single, = exe.run(main, feed=feed, fetch_list=[out])
    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    with parallel.mesh_guard(mesh):
        sharded, = exe.run(main, feed=feed, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(single),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_headful_bias():
    """A bias with a full head axis is sliced to each device's
    post-all-to-all head tile (the transformer's materialised attn-bias
    path)."""
    from paddle_tpu.kernels import ulysses_attention_sharded

    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    q, k, v = make_qkv(b=2, h=4, lq=32, lk=32, d=8)
    bias = np.random.RandomState(3).randn(2, 4, 32, 32).astype(
        np.float32) * 0.5
    bias = jnp.asarray(bias)
    out = ulysses_attention_sharded(mesh, q, k, v, bias=bias,
                                    dp_axis=None)
    ref = naive_attention(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


def test_ulysses_dropout_runs_and_differs():
    """Ulysses attention-prob dropout: deterministic per seed,
    differentiable, and the head-tile masks are decorrelated — no two
    sequence shards (= head tiles after the all-to-all) produce
    identical keep patterns."""
    from paddle_tpu.kernels import ulysses_attention_sharded

    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    q, k, v = make_qkv(b=2, h=4, lq=32, lk=32, d=8)
    clean = ulysses_attention_sharded(mesh, q, k, v, dp_axis=None)
    drop = ulysses_attention_sharded(mesh, q, k, v, dp_axis=None,
                                     dropout_rate=0.4, dropout_seed=5)
    assert not np.allclose(np.asarray(clean), np.asarray(drop))
    drop2 = ulysses_attention_sharded(mesh, q, k, v, dp_axis=None,
                                      dropout_rate=0.4, dropout_seed=5)
    np.testing.assert_array_equal(np.asarray(drop), np.asarray(drop2))
    # decorrelation across head tiles: with IDENTICAL q/k/v per head,
    # identical masks would give identical per-head outputs
    q1 = jnp.broadcast_to(q[:, :1], q.shape)
    k1 = jnp.broadcast_to(k[:, :1], k.shape)
    v1 = jnp.broadcast_to(v[:, :1], v.shape)
    d1 = np.asarray(ulysses_attention_sharded(
        mesh, q1, k1, v1, dp_axis=None, dropout_rate=0.4,
        dropout_seed=5))
    pairs_equal = [np.allclose(d1[:, a], d1[:, b])
                   for a in range(4) for b in range(a + 1, 4)]
    assert not any(pairs_equal), "head-tile dropout masks are correlated"
    g = jax.grad(lambda q: ulysses_attention_sharded(
        mesh, q, k, v, dp_axis=None, dropout_rate=0.4,
        dropout_seed=5).sum())(q)
    assert np.isfinite(np.asarray(g)).all()
