"""Dataset loaders: download+md5+cache plumbing (common.py) exercised
through file:// URLs, REAL parse paths exercised on tiny generated
fixtures (idx/pickle-tar/whitespace/ml-1m/tab-pairs), and the explicit
synthetic fallback contract — all without egress.
"""

import gzip
import os
import pickle
import struct
import tarfile
import zipfile

import numpy as np
import pytest

from paddle_tpu.datasets import (cifar, common, conll05, imdb, imikolov,
                                 mnist, movielens, uci_housing, wmt16)


# -- common.download --------------------------------------------------------

def _file_url(p):
    return "file://" + str(p)


def test_download_caches_and_verifies(tmp_path, monkeypatch):
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path / "home"))
    src = tmp_path / "payload.bin"
    src.write_bytes(b"hello dataset")
    md5 = common.md5file(str(src))
    got = common.download(_file_url(src), "mod", md5)
    assert open(got, "rb").read() == b"hello dataset"
    # cached: works even after the source disappears
    src.unlink()
    again = common.download(_file_url(src), "mod", md5)
    assert again == got


def test_download_md5_mismatch_raises(tmp_path, monkeypatch):
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path / "home"))
    src = tmp_path / "x.bin"
    src.write_bytes(b"AAAA")
    with pytest.raises(common.DownloadError, match="md5 mismatch"):
        common.download(_file_url(src), "mod", "0" * 32)
    # nothing half-written remains
    mod_dir = tmp_path / "home" / "mod"
    assert not any(f.endswith(".bin") for f in os.listdir(mod_dir))


def test_download_stale_cache_refetches(tmp_path, monkeypatch):
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path / "home"))
    src = tmp_path / "y.bin"
    src.write_bytes(b"v2 content")
    md5 = common.md5file(str(src))
    # poison the cache with stale bytes
    cached = tmp_path / "home" / "mod" / "y.bin"
    cached.parent.mkdir(parents=True)
    cached.write_bytes(b"old")
    got = common.download(_file_url(src), "mod", md5)
    assert open(got, "rb").read() == b"v2 content"


def test_download_unreachable_raises(tmp_path, monkeypatch):
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path / "home"))
    with pytest.raises(common.DownloadError):
        common.download(_file_url(tmp_path / "missing.bin"), "mod", None)


# -- real parse paths on fixtures -------------------------------------------

def test_mnist_parse_idx(tmp_path):
    imgs = (np.arange(3 * 784) % 256).astype(np.uint8).reshape(3, 784)
    labels = np.array([3, 1, 4], np.uint8)
    ip = str(tmp_path / "img.gz")
    lp = str(tmp_path / "lbl.gz")
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 3, 28, 28))
        f.write(imgs.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, 3))
        f.write(labels.tobytes())
    rows = list(mnist.parse_idx(ip, lp)())
    assert len(rows) == 3
    np.testing.assert_allclose(
        rows[0][0], imgs[0].astype(np.float32) / 255 * 2 - 1, atol=1e-6)
    assert [r[1] for r in rows] == [3, 1, 4]


def test_cifar_parse_tar(tmp_path):
    p = str(tmp_path / "cifar.tar.gz")
    batch = {b"data": (np.arange(2 * 3072) % 255).reshape(2, 3072)
             .astype(np.uint8),
             b"labels": [7, 2]}
    import io as pyio

    with tarfile.open(p, "w:gz") as tar:
        blob = pickle.dumps(batch)
        info = tarfile.TarInfo("cifar-10-batches-py/data_batch_1")
        info.size = len(blob)
        tar.addfile(info, pyio.BytesIO(blob))
    rows = list(cifar.parse_cifar(p, "data_batch")())
    assert len(rows) == 2 and rows[0][1] == 7
    assert rows[0][0].shape == (3072,) and rows[0][0].max() <= 1.0


def test_housing_parse(tmp_path):
    rng = np.random.RandomState(0)
    table = np.hstack([rng.rand(10, 13) * 100, rng.rand(10, 1) * 50])
    p = str(tmp_path / "housing.data")
    np.savetxt(p, table)
    train_rows, test_rows = uci_housing.parse_housing(p)
    assert len(train_rows) == 8 and len(test_rows) == 2
    x, y = train_rows[0]
    assert x.shape == (13,) and y.shape == (1,)
    # min-max normalized on the train split -> bounded
    allx = np.stack([r[0] for r in train_rows])
    assert allx.min() >= -0.5 - 1e-6 and allx.max() <= 0.5 + 1e-6


def test_imdb_parse_and_dict(tmp_path):
    import io as pyio

    p = str(tmp_path / "aclImdb.tar.gz")
    docs = {
        "aclImdb/train/pos/0_9.txt": b"great great movie <br />fun",
        "aclImdb/train/neg/0_2.txt": b"terrible terrible terrible plot",
    }
    with tarfile.open(p, "w:gz") as tar:
        for name, text in docs.items():
            info = tarfile.TarInfo(name)
            info.size = len(text)
            tar.addfile(info, pyio.BytesIO(text))
    wd = imdb.build_dict_from_tar(
        p, r"aclImdb/train/(pos|neg)/.*\.txt$", cutoff=1)
    assert "great" in wd and "terrible" in wd
    rows = list(imdb.parse_imdb(p, wd, r"aclImdb/train/pos/.*",
                                r"aclImdb/train/neg/.*")())
    assert len(rows) == 2
    labels = sorted(r[1] for r in rows)
    assert labels == [0, 1]


def test_imikolov_parse(tmp_path):
    import io as pyio

    p = str(tmp_path / "simple-examples.tgz")
    text = b"the cat sat\nthe dog sat on the mat\n"
    with tarfile.open(p, "w:gz") as tar:
        for member in (imikolov.TRAIN_MEMBER, imikolov.TEST_MEMBER):
            info = tarfile.TarInfo(member)
            info.size = len(text)
            tar.addfile(info, pyio.BytesIO(text))
    wd = imikolov.build_dict_from_tar(p, min_word_freq=1)
    assert "the" in wd and "<unk>" in wd
    grams = list(imikolov.parse_ngrams(p, imikolov.TRAIN_MEMBER, wd, 3)())
    assert all(len(g) == 3 for g in grams)
    assert len(grams) > 0


def test_movielens_parse(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_SYNTHETIC", "")
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path / "home"))
    zp = tmp_path / "ml-1m.zip"
    with zipfile.ZipFile(zp, "w") as z:
        z.writestr("ml-1m/users.dat",
                   "1::M::25::4::12345\n2::F::35::7::67890\n")
        z.writestr("ml-1m/movies.dat",
                   "10::Toy Story (1995)::Animation|Comedy\n"
                   "20::Heat (1995)::Action\n")
        z.writestr("ml-1m/ratings.dat",
                   "1::10::5::978300760\n2::20::3::978302109\n"
                   "1::20::4::978301968\n2::10::2::978300275\n"
                   "1::10::4::978824291\n2::20::5::978302268\n"
                   "1::20::3::978302039\n2::10::4::978300719\n"
                   "1::10::5::978824268\n2::20::1::978824351\n")
    md5 = common.md5file(str(zp))
    monkeypatch.setattr(movielens, "URL", _file_url(zp))
    monkeypatch.setattr(movielens, "MD5", md5)
    movielens._cache = None
    try:
        rows = list(movielens.train()())
        test_rows = list(movielens.test()())
        assert len(rows) == 9 and len(test_rows) == 1
        uid, gender, age, job, mid, gl, tl, score = rows[0]
        assert uid == 1 and gender == 0 and age == 2 and job == 4
        assert mid == 10 and len(gl) == 2 and len(tl) == 2
        assert score.shape == (1,)
        assert movielens.max_user_id() == 3
        assert movielens.max_movie_id() == 21
        assert len(movielens.movie_categories()) == 3
    finally:
        movielens._cache = None


def test_wmt16_parse(tmp_path):
    import io as pyio

    p = str(tmp_path / "wmt16.tar.gz")
    pairs = b"the cat\tdie katze\na dog\tein hund\n"
    with tarfile.open(p, "w:gz") as tar:
        for member in ("wmt16/train", "wmt16/test"):
            info = tarfile.TarInfo(member)
            info.size = len(pairs)
            tar.addfile(info, pyio.BytesIO(pairs))
    src_d = wmt16.build_dict_from_tar(p, "wmt16/train", 0, 100)
    trg_d = wmt16.build_dict_from_tar(p, "wmt16/train", 1, 100)
    assert src_d["<s>"] == 0 and "cat" in src_d and "katze" in trg_d
    rows = list(wmt16.parse_pairs(p, "wmt16/train", src_d, trg_d)())
    assert len(rows) == 2
    src, trg_next, trg_in = rows[0]
    assert trg_in[0] == wmt16.START and trg_next[-1] == wmt16.END
    assert len(trg_in) == len(trg_next)


# -- fallback contract ------------------------------------------------------

def test_fixture_fallback_warns_and_serves(monkeypatch):
    # unreachable URLs (no egress in CI) -> committed REAL-data fixture
    common._warned.clear()
    monkeypatch.setenv("PADDLE_TPU_SYNTHETIC", "")
    monkeypatch.setattr(
        mnist, "TRAIN_IMAGE_URL", "file:///nonexistent/i.gz")
    with pytest.warns(UserWarning, match="fixture"):
        r = mnist.train()
    assert mnist.LAST_TIER == "fixture"
    rows = list(r())
    assert len(rows) == 1500
    img, label = next(iter(rows))
    assert img.shape == (784,) and 0 <= label < 10
    assert -1.0 <= img.min() and img.max() <= 1.0
    # all ten classes present in the stratified fixture split
    assert sorted({lb for _, lb in rows}) == list(range(10))


def test_synthetic_fallback_warns_and_serves(monkeypatch):
    # fixture ALSO unavailable -> loud synthetic fallback, right schema
    common._warned.clear()
    monkeypatch.setenv("PADDLE_TPU_SYNTHETIC", "")
    monkeypatch.setattr(
        mnist, "TRAIN_IMAGE_URL", "file:///nonexistent/i.gz")
    monkeypatch.setattr(mnist, "FIXTURE_DIR", "/nonexistent")
    with pytest.warns(UserWarning, match="SYNTHETIC"):
        r = mnist.train()
    assert mnist.LAST_TIER == "synthetic"
    img, label = next(r())
    assert img.shape == (784,) and 0 <= label < 10


def test_wmt16_fixture_tier(monkeypatch):
    """The committed CLDR corpus serves the wmt16 reader protocol with a
    shared train-built vocabulary and near-zero test-side UNKs."""
    common._warned.clear()
    monkeypatch.setenv("PADDLE_TPU_SYNTHETIC", "")
    monkeypatch.setattr(
        wmt16, "URL", "file:///nonexistent/wmt16.tar.gz")
    wmt16._dict_cache.clear()
    train_rows = list(wmt16.train(4000)())
    assert wmt16.LAST_TIER == "fixture"
    test_rows = list(wmt16.test(4000)())
    assert len(train_rows) > 6000 and len(test_rows) == 400
    src, trg_next, trg_in = train_rows[0]
    assert trg_in[0] == wmt16.START and trg_next[-1] == wmt16.END
    assert trg_in[1:] == trg_next[:-1]
    # vocab built from train covers the test combinations (by design
    # the test split reuses train vocabulary): UNK rate ~0
    flat = [w for r in test_rows for w in r[0]]
    assert flat.count(wmt16.UNK) / len(flat) < 0.01
    d_en = wmt16.get_dict("en", 4000)
    d_de = wmt16.get_dict("de", 4000)
    assert len(d_en) > 1000 and len(d_de) > 1000 and d_en != d_de


def test_forced_synthetic_env(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_SYNTHETIC", "1")
    rows = list(uci_housing.test()())
    assert len(rows) == uci_housing.TEST_N
    x, y = rows[0]
    assert x.shape == (13,) and y.shape == (1,)
    # deterministic
    rows2 = list(uci_housing.test()())
    np.testing.assert_array_equal(rows[5][0], rows2[5][0])


def test_all_synthetic_schemas(monkeypatch):
    """Every module serves schema-correct synthetic data offline."""
    monkeypatch.setenv("PADDLE_TPU_SYNTHETIC", "1")
    img, lbl = next(cifar.train10()())
    assert img.shape == (3072,) and 0 <= lbl < 10
    seq, lbl = next(imdb.train()())
    assert isinstance(seq, list) and lbl in (0, 1)
    gram = next(imikolov.train(None, 5)())
    assert len(gram) == 5
    row = next(movielens.train()())
    assert len(row) == 8
    cols = next(conll05.test()())
    assert len(cols) == 9 and len(cols[0]) == len(cols[8])
    src, trg_next, trg_in = next(wmt16.train()())
    assert trg_in[0] == wmt16.START and trg_next[-1] == wmt16.END


def test_conll05_parse(tmp_path, monkeypatch):
    import io as pyio

    monkeypatch.setenv("PADDLE_TPU_SYNTHETIC", "")
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path / "home"))
    words = "The\ncat\nsat\nquickly\n\nDogs\nbark\n\n"
    # sentence 1: two predicates (sat, quickly-col is pred2's args)
    props = ("-\t(A0*\t*\n"
             "-\t*)\t(A1*)\n"
             "sat\t(V*)\t*\n"
             "ran\t*\t(V*)\n"
             "\n"
             "-\t(A0*)\n"
             "bark\t(V*)\n"
             "\n")
    tp = tmp_path / "conll05st-tests.tar.gz"
    with tarfile.open(tp, "w:gz") as tar:
        for name, text in (("conll05st-release/test.wsj/words/"
                            "test.wsj.words", words),
                           ("conll05st-release/test.wsj/props/"
                            "test.wsj.props", props)):
            b = text.encode()
            info = tarfile.TarInfo(name)
            info.size = len(b)
            tar.addfile(info, pyio.BytesIO(b))
    monkeypatch.setattr(conll05, "DATA_URL", _file_url(tp))
    monkeypatch.setattr(conll05, "DATA_MD5", common.md5file(str(tp)))
    conll05._real_cache = None
    try:
        word_d, verb_d, label_d = conll05.get_dict()
        assert "cat" in word_d and "sat" in verb_d and "B-A0" in label_d
        rows = list(conll05.test()())
        # sentence 1 yields 2 samples (one per predicate), sentence 2 one
        assert len(rows) == 3
        s1p1, s1p2, s2 = rows
        # p-th predicate's mark matches the p-th verb row (r2 review:
        # verb/mark used to always point at the first predicate)
        assert s1p1[7] == [0, 0, 1, 0]       # mark for 'sat'
        assert s1p2[7] == [0, 0, 0, 1]       # mark for 'ran'
        assert s1p1[6][0] == verb_d["sat"]
        assert s1p2[6][0] == verb_d["ran"]
        # tags come from the matching column
        assert s1p1[8][0] == label_d["B-A0"]
        assert s1p2[8][1] == label_d["B-A1"]
        # every id is in-vocab for model building off get_dict() lens
        assert max(s1p1[0]) < len(word_d)
    finally:
        conll05._real_cache = None


# -- r3 modules (VERDICT r2 missing#6): wmt14, flowers, voc2012,
# sentiment, mq2007 + image transforms --------------------------------------

def test_wmt14_parse(tmp_path):
    import io as pyio

    from paddle_tpu.datasets import wmt14

    p = str(tmp_path / "wmt14.tgz")
    src_dict = b"<s>\n<e>\n<unk>\nthe\ncat\n"
    trg_dict = b"<s>\n<e>\n<unk>\nle\nchat\n"
    pairs = b"the cat\tle chat\nthe the\tle le\n"
    with tarfile.open(p, "w:gz") as tar:
        for name, data in [("wmt14/src.dict", src_dict),
                           ("wmt14/trg.dict", trg_dict),
                           ("wmt14/train/train", pairs)]:
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tar.addfile(info, pyio.BytesIO(data))
    rows = list(wmt14.parse_wmt14(p, "train/train", dict_size=100))
    assert len(rows) == 2
    src, trg, trg_next = rows[0]
    # <s> the cat <e> / <s> le chat / le chat <e>
    assert src == [0, 3, 4, 1]
    assert trg == [0, 3, 4]
    assert trg_next == [3, 4, 1]


def test_mq2007_parse_formats():
    from paddle_tpu.datasets import mq2007

    feats1 = " ".join(f"{i+1}:0.{i+1}" for i in range(46))
    feats2 = " ".join(f"{i+1}:0.0" for i in range(46))
    lines = [
        f"2 qid:10 {feats1} # doc A",
        f"0 qid:10 {feats2} # doc B",
        f"1 qid:11 {feats1} # doc C",
    ]
    groups = mq2007.parse_letor_lines(lines)
    assert [g[0] for g in groups] == [10, 11]
    assert [len(g[1]) for g in groups] == [2, 1]
    assert groups[0][1][0][0] == 2
    np.testing.assert_allclose(groups[0][1][0][1][0], 0.1)

    points = list(mq2007._emit(groups, "pointwise"))
    assert len(points) == 3 and points[0][1].shape == (46,)
    pairs = list(mq2007._emit(groups, "pairwise"))
    assert len(pairs) == 1                  # only the rel-2 vs rel-0 pair
    label, better, worse = pairs[0]
    np.testing.assert_allclose(better, groups[0][1][0][1])
    lists = list(mq2007._emit(groups, "listwise"))
    assert lists[0][1].shape == (2, 46)


def test_sentiment_parse_zip(tmp_path):
    from paddle_tpu.datasets import sentiment

    p = str(tmp_path / "movie_reviews.zip")
    import zipfile

    with zipfile.ZipFile(p, "w") as z:
        z.writestr("movie_reviews/neg/cv000.txt", "bad bad film .")
        z.writestr("movie_reviews/pos/cv001.txt", "good good good film !")
    rows, word_dict = sentiment.load_sentiment_data(p)
    assert len(rows) == 2
    assert rows[0][1] == 0 and rows[1][1] == 1     # neg, pos interleaved
    # 'good' (3 uses) outranks 'bad' (2): lower id
    assert word_dict["good"] < word_dict["bad"]
    ids_neg = rows[0][0]
    assert ids_neg == [word_dict["bad"], word_dict["bad"],
                       word_dict["film"], word_dict["."]]


def test_voc2012_parse_tar(tmp_path):
    import io as pyio

    from PIL import Image

    from paddle_tpu.datasets import voc2012

    p = str(tmp_path / "voc.tar")

    def png_bytes(arr, mode):
        buf = pyio.BytesIO()
        Image.fromarray(arr, mode).save(buf, "PNG")
        return buf.getvalue()

    def jpg_bytes(arr):
        buf = pyio.BytesIO()
        Image.fromarray(arr, "RGB").save(buf, "JPEG")
        return buf.getvalue()

    img = (np.arange(4 * 4 * 3) % 255).astype(np.uint8).reshape(4, 4, 3)
    lab = (np.arange(16) % 3).astype(np.uint8).reshape(4, 4)
    with tarfile.open(p, "w") as tar:
        for name, data in [
                (voc2012.SET_FILE.format("val"), b"img0\n"),
                (voc2012.DATA_FILE.format("img0"), jpg_bytes(img)),
                (voc2012.LABEL_FILE.format("img0"),
                 png_bytes(lab, "L"))]:
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tar.addfile(info, pyio.BytesIO(data))
    rows = list(voc2012.parse_voc2012(p, "val"))
    assert len(rows) == 1
    data, label = rows[0]
    assert data.shape == (4, 4, 3) and label.shape == (4, 4)
    np.testing.assert_array_equal(label, lab)


def test_image_transforms():
    from paddle_tpu.datasets import image

    im = (np.arange(20 * 30 * 3) % 255).astype(np.uint8).reshape(20, 30, 3)
    r = image.resize_short(im, 10)
    assert min(r.shape[:2]) == 10 and r.shape[2] == 3
    c = image.center_crop(r, 8)
    assert c.shape[:2] == (8, 8)
    chw = image.to_chw(c)
    assert chw.shape == (3, 8, 8)
    flipped = image.left_right_flip(c)
    np.testing.assert_array_equal(flipped[:, 0], c[:, -1])
    out = image.simple_transform(im, 12, 8, is_train=False)
    assert out.shape == (3, 8, 8) and out.dtype == np.float32
    assert 0.0 <= out.min() and out.max() <= 1.0
    out_t = image.simple_transform(im, 12, 8, is_train=True,
                                   rng=np.random.RandomState(0))
    assert out_t.shape == (3, 8, 8)
    # PNG round-trip through load_image_bytes
    import io as pyio

    from PIL import Image

    buf = pyio.BytesIO()
    Image.fromarray(im, "RGB").save(buf, "PNG")
    back = image.load_image_bytes(buf.getvalue())
    np.testing.assert_array_equal(back, im)


def test_r3_synthetic_schemas(monkeypatch):
    """All five r3 modules serve schema-correct synthetic rows offline."""
    from paddle_tpu.datasets import (flowers, mq2007, sentiment, voc2012,
                                     wmt14)

    monkeypatch.setenv("PADDLE_TPU_SYNTHETIC", "1")
    src, trg, nxt = next(wmt14.train(1000)())
    assert src[0] == wmt14.START_ID and nxt[-1] == wmt14.END_ID
    assert len(trg) == len(nxt)

    img, lab = next(flowers.train()())
    assert img.shape[0] == 3 and img.dtype == np.float32
    assert 0 <= lab < flowers.N_CLASSES

    im, seg = next(voc2012.train()())
    assert im.ndim == 3 and seg.ndim == 2 and im.shape[:2] == seg.shape

    ids, pol = next(sentiment.train()())
    assert pol in (0, 1) and all(isinstance(i, (int, np.integer))
                                 for i in ids)

    label, better, worse = next(mq2007.train("pairwise")())
    assert better.shape == (mq2007.N_FEATURES,)
    rel, feat = next(mq2007.train("pointwise")())
    assert feat.shape == (mq2007.N_FEATURES,)


def test_mq2007_zip_auto_extract(tmp_path, monkeypatch):
    """A zip archive dropped in (or fetched into) the cache dir is
    extracted automatically — the stdlib-extractable path the official
    .rar cannot offer (r3 VERDICT missing#7)."""
    import io
    import zipfile

    from paddle_tpu.datasets import common, mq2007

    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    monkeypatch.delenv("PADDLE_TPU_SYNTHETIC", raising=False)  # conftest sets it
    base = common.cache_dir("mq2007")
    line = ("2 qid:10 " +
            " ".join(f"{i+1}:{(i % 5) * 0.1:.1f}" for i in range(46)) +
            " # doc1\n")
    line2 = ("0 qid:10 " +
             " ".join(f"{i+1}:{(i % 7) * 0.05:.2f}" for i in range(46)) +
             " # doc2\n")
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("MQ2007/Fold1/train.txt", line + line2)
    import os
    with open(os.path.join(base, "MQ2007.zip"), "wb") as f:
        f.write(buf.getvalue())

    rows = list(mq2007.train(format="pointwise")())
    assert len(rows) == 2
    rel, feat = rows[0]
    assert rel == 2 and feat.shape == (46,)
