"""Multi-process fault-tolerance scenarios (marked slow; tier-1 runs the
fast deterministic halves in test_resilience.py).

The flagship test is the chaos end-to-end: a seeded FaultInjector
SIGKILLs the worker mid-epoch (kill-after-N-leases) while the test
restarts the master out from under it; the supervised launcher respawns
the worker, ResilientTrainer resumes from the newest valid checkpoint,
the recovered master re-dispatches the expired leases, and the job
finishes with every chunk processed and zero lost tasks — the
reference's whole fault-tolerance story (go/master/service.go +
go/pserver/service.go) in one deterministic scenario.
"""

import os
import sys
import textwrap
import threading
import time

import pytest

from paddle_tpu.launch import launch
from paddle_tpu.parallel import MasterServer, TaskQueue
from paddle_tpu.resilience import FaultInjector

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow


def _clean_env(extra=None):
    """CPU-only env for spawned workers (same hygiene as
    test_distributed_multiproc._run: no TPU tunnel, repo on path)."""
    env = {"JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                            "")}
    env.update(extra or {})
    return env


# -- elastic launcher --------------------------------------------------------

CRASHY = """
    import os, sys
    marker_dir = sys.argv[1]
    n = len(os.listdir(marker_dir))
    open(os.path.join(marker_dir, f"inc-{n}"), "w").close()
    if n < 2:
        os._exit(7)          # die hard on the first two incarnations
    sys.exit(0)
"""


def test_elastic_launcher_restarts_dead_rank_until_success(tmp_path):
    """--max-restarts: a rank dying non-zero is respawned (same rank,
    same env) until it succeeds or the budget runs out."""
    script = str(tmp_path / "crashy.py")
    open(script, "w").write(textwrap.dedent(CRASHY))
    mdir = str(tmp_path / "marks")
    os.makedirs(mdir)
    rc = launch(1, [script, mdir], env_extra=_clean_env(),
                max_restarts=3, kill_grace=2.0)
    assert rc == 0
    assert len(os.listdir(mdir)) == 3            # 1 first run + 2 restarts


def test_elastic_launcher_budget_exhaustion_fails_fast(tmp_path):
    script = str(tmp_path / "crashy.py")
    open(script, "w").write(textwrap.dedent(CRASHY))
    mdir = str(tmp_path / "marks")
    os.makedirs(mdir)
    rc = launch(1, [script, mdir], env_extra=_clean_env(),
                max_restarts=1, kill_grace=2.0)
    assert rc == 7                               # second crash is fatal
    assert len(os.listdir(mdir)) == 2


def test_launcher_writes_per_rank_logs_across_restarts(tmp_path):
    script = str(tmp_path / "talky.py")
    open(script, "w").write(textwrap.dedent("""
        import os, sys
        mark = sys.argv[1]
        first = not os.path.exists(mark)
        open(mark, "a").close()
        print("hello from incarnation", flush=True)
        sys.exit(1 if first else 0)
    """))
    logdir = str(tmp_path / "logs")
    rc = launch(1, [script, str(tmp_path / "mark")],
                env_extra=_clean_env(), max_restarts=2, kill_grace=2.0,
                log_dir=logdir)
    assert rc == 0
    log = open(os.path.join(logdir, "rank-0.log")).read()
    # both incarnations appended to the same rank log
    assert log.count("hello from incarnation") == 2


# -- the chaos end-to-end ----------------------------------------------------

E2E_WORKER = """
    import os, sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    addr, ckpt_dir, losses_path = sys.argv[1:4]

    from paddle_tpu import fluid
    from paddle_tpu.parallel import MasterClient
    from paddle_tpu.resilience import ResilientTrainer

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data("x", [4], "float32")
        y = fluid.layers.data("y", [1], "float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())

    W = np.array([1.0, -2.0, 0.5, 3.0], np.float32)

    def read_chunk(seed):
        r = np.random.RandomState(seed)
        out = []
        for _ in range(4):                  # 4 record-batches per chunk
            xs = r.randn(8, 4).astype(np.float32)
            out.append((xs, xs @ W[:, None]))
        return out

    client = MasterClient(addr, worker=f"pid-{os.getpid()}")
    trainer = ResilientTrainer(ckpt_dir, client, read_chunk,
                               program=main, scope=scope,
                               save_interval_steps=1, poll_interval=0.05)

    def train_step(rec, step):
        xs = np.asarray(rec[0], np.float32)
        ys = np.asarray(rec[1], np.float32)
        l, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        with open(losses_path, "a") as f:
            f.write(f"{step} {float(np.asarray(l))}\\n")

    fresh = []
    with fluid.scope_guard(scope):
        final = trainer.run(train_step,
                            init_fn=lambda: (fresh.append(1),
                                             exe.run(startup)))
    if not fresh:
        print("RESUMED-FROM-CHECKPOINT", flush=True)
    print("WORKER-DONE step", final, flush=True)
"""

N_CHUNKS = 8


def test_chaos_end_to_end_worker_kills_and_master_restart(tmp_path):
    """Acceptance scenario: seeded chaos SIGKILLs the worker upon its
    3rd lease of every incarnation, the test restarts the master
    mid-epoch (recovering from its auto-snapshot), the supervised
    launcher respawns the worker, and the job still completes: all 8
    chunks done, 0 lost, loss decreased, ResilientTrainer resumed from a
    checkpoint, and every journaled injection decision replays exactly
    from the seed."""
    script = str(tmp_path / "worker.py")
    open(script, "w").write(textwrap.dedent(E2E_WORKER))
    snap = str(tmp_path / "master.snap")
    ckpt = str(tmp_path / "ckpt")
    losses_path = str(tmp_path / "losses.txt")
    journal = str(tmp_path / "chaos.journal")
    logdir = str(tmp_path / "logs")
    seed = 7

    queue = TaskQueue(timeout_secs=1.0, failure_max=10)
    queue.set_dataset(list(range(N_CHUNKS)))
    server = MasterServer(queue, snapshot_path=snap, snapshot_every=1)
    addr = server.start()
    host, port = addr.split(":")

    env = _clean_env({
        "PADDLE_TPU_CHAOS": "master.http=0.05",
        "PADDLE_TPU_CHAOS_SEED": str(seed),
        "PADDLE_TPU_CHAOS_KILL_AFTER": "3",
        "PADDLE_TPU_CHAOS_LOG": journal,
    })
    rc_box = {}

    def run_job():
        rc_box["rc"] = launch(
            1, [script, addr, ckpt, losses_path], env_extra=env,
            max_restarts=12, kill_grace=5.0, log_dir=logdir)

    th = threading.Thread(target=run_job)
    th.start()

    # let the first incarnation make progress, then crash the master
    deadline = time.monotonic() + 180
    while (time.monotonic() < deadline
           and server.queue.counts()["done"] < 2):
        time.sleep(0.1)
    assert server.queue.counts()["done"] >= 2, "worker never progressed"
    server.stop()                                # snapshot + gone
    time.sleep(0.5)                              # worker retries meanwhile
    server2 = MasterServer(None, host=host, port=int(port),
                           snapshot_path=snap)
    server2.start()

    th.join(timeout=420)
    assert not th.is_alive(), "supervised job did not finish"
    try:
        assert rc_box["rc"] == 0

        # 0 lost tasks: every chunk processed, none discarded or leased
        counts = server2.queue.counts()
        assert counts["done"] == N_CHUNKS, counts
        assert counts["failed"] == 0 and counts["pending"] == 0, counts
        assert server2.queue.all_done()

        # worker actually died and was respawned by the supervisor, and
        # at least one incarnation resumed from a checkpoint
        log = open(os.path.join(logdir, "rank-0.log")).read()
        assert "RESUMED-FROM-CHECKPOINT" in log
        assert log.count("WORKER-DONE") == 1     # exactly one clean exit
        kills = [ln for ln in open(journal) if ln.startswith("# kill-self")]
        assert kills, "chaos never killed the worker"

        # training made progress across all the carnage
        losses = [float(ln.split()[1]) for ln in open(losses_path)]
        assert len(losses) >= N_CHUNKS * 4       # every record trained on
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

        # determinism: every journaled draw replays exactly from the
        # seed, and repeated (point, index) pairs — the same draw made
        # by different incarnations — agree bit-for-bit, which is what
        # "same seed, same injection schedule on re-run" means
        draws = {}
        n_lines = 0
        for ln in open(journal):
            if ln.startswith("#") or not ln.strip():
                continue
            point, index, value, fired = ln.split()
            n_lines += 1
            want = FaultInjector.decision(seed, point, int(index))
            assert abs(float(value) - want) < 1e-9
            prev = draws.setdefault((point, int(index)), (value, fired))
            assert prev == (value, fired)
        assert n_lines > 0 and len(draws) < n_lines, \
            "expected repeated draws across worker incarnations"
    finally:
        server2.stop()
