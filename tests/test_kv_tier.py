"""Tiered KV cache & session tests (ISSUE 20): bitwise decode parity
across evict->spill->reload round-trips for fp32/bf16/int8 pools (scale
sidecars travel with the pages), session suspend/resume through the
checksummed host/disk artifact with token-for-token continuation parity,
the seeded ``kv.spill_corrupt`` chaos point degrading a torn artifact to
re-prefill (never wrong tokens), zero recompiles with tiering active,
the scheduler's always-emitted ``kv_bytes_per_token`` + tier/spill stats
schema, and the gateway session API (journal replay included)."""

import time

import numpy as np
import pytest

from paddle_tpu import fluid
from paddle_tpu.resilience.chaos import FaultInjector, install
from paddle_tpu.serving import (ContinuousBatchingScheduler,
                                PagedTransformerGenerator, SessionStore,
                                TransformerGenerator)

V, NL, NH, DK, DM, DI = 24, 2, 2, 4, 16, 32
SRC, OUT, PS, CHUNK = 8, 16, 4, 4
# probed prompt: greedy decode under seed-7 params emits no end_id for
# >= 12 steps, so suspend/resume legs never retire early on end tokens
PROMPT = np.array([14, 17, 23, 2, 5, 5], np.int64)


@pytest.fixture(autouse=True)
def _inert_chaos():
    prev = install(FaultInjector())
    yield
    install(prev)


def _mk(kv_dtype, store, prefix, host_pages=16, demote_watermark=0,
        seed=7):
    """A tiered paged generator sharing a randomly-initialized scope
    with the dense decoder (same weight-init recipe as the paged parity
    suite — dense.init_params seeds both)."""
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    kw = dict(n_layer=NL, n_head=NH, d_key=DK, d_value=DK, d_model=DM,
              d_inner_hid=DI, max_length=64, src_len=SRC, scope=scope,
              executor=exe, param_prefix=prefix)
    dense = TransformerGenerator(V, V, max_out_len=OUT,
                                 causal_encoder=True, **kw)
    gen = PagedTransformerGenerator(V, V, max_out_len=OUT, page_size=PS,
                                    chunk_size=CHUNK, num_pages=64,
                                    kv_dtype=kv_dtype,
                                    host_pages=host_pages,
                                    session_store=store,
                                    demote_watermark=demote_watermark,
                                    **kw)
    dense.init_params(seed=seed)
    return gen


@pytest.fixture(scope="module", params=["float32", "bfloat16", "int8"])
def tiered(request, tmp_path_factory):
    """One tiered generator per kv dtype, shared across this module's
    tests (each test resets lane state via open_slots), plus the
    uninterrupted greedy reference decode of PROMPT."""
    kv_dtype = request.param
    store = SessionStore(
        dirname=str(tmp_path_factory.mktemp(f"kvs-{kv_dtype}")))
    gen = _mk(kv_dtype, store, prefix=f"tft{kv_dtype[:3]}")
    srcp = np.zeros((1, SRC), np.int64)
    srcp[0, :len(PROMPT)] = PROMPT
    ref = [int(t) for t in
           gen.greedy(srcp, [len(PROMPT)], max_new=12,
                      stop_at_end=False)[0]]
    assert gen.end_id not in ref[:10], \
        "probed prompt regressed; pick another"
    return gen, store, ref


def _decode(gen, slot, want, toks):
    for _ in range(4 * OUT):
        if len(toks) >= want:
            return
        out = gen.lane_step()
        if slot in out:
            toks.append(int(out[slot]))
    raise AssertionError(f"lane never produced {want} tokens: {toks}")


# -- suspend / resume ---------------------------------------------------------

def test_suspend_resume_token_parity(tiered):
    """Decode 4 tokens, suspend the lane to a host/disk artifact, resume
    into a (different) slot, decode on: the continuation is bitwise the
    tokens an uninterrupted decode produces — for fp32, bf16 AND int8
    pools (the int8 artifact carries the fp32 scale sidecar rows)."""
    gen, store, ref = tiered
    gen.open_slots(2)
    gen.admit_slot(0, PROMPT, max_new=10)
    toks = []
    _decode(gen, 0, 4, toks)
    assert toks == ref[:4]
    assert gen.detach_slot(0, "parity-1"), "detach refused a decode lane"
    # the spill completes OFF the retire path, in the maintenance slice
    assert gen.tier_maintenance()
    assert gen.cache_stats()["tiers"]["suspends"] >= 1
    got = store.get("parity-1", gen.session_fingerprint())
    assert got is not None, "artifact unreadable after suspend"
    meta, arrays = got
    assert meta["pos"] == 4
    if gen.kv_dtype == "int8":
        assert "cross_scales" in arrays and "self_scales" in arrays, \
            "int8 scale sidecars must travel with the pages"
    # resume into the OTHER slot: placement must not matter
    res = gen.resume_slot(1, "parity-1")
    assert res is not None and res["pos"] == 4
    _decode(gen, 1, 10, toks)
    assert toks == ref[:10], (gen.kv_dtype, toks, ref)
    gen.clear_slot(1)
    # unknown session: clean miss, counted
    misses0 = gen._tier_stats["resume_misses"]
    assert gen.resume_slot(0, "never-stored") is None
    assert gen._tier_stats["resume_misses"] == misses0 + 1


def test_evict_spill_reload_bitwise(tiered):
    """Cached chunks demoted to host RAM and promoted back land on
    fresh pages with bitwise-identical bytes (pool rows AND, for int8,
    the scale sidecar rows)."""
    gen, _, _ = tiered
    gen.open_slots(1)
    gen.admit_slot(0, PROMPT, max_new=2)
    toks = []
    _decode(gen, 0, 2, toks)
    gen.clear_slot(0)           # the prompt chunk goes evictable
    a = gen.alloc
    assert len(a._chunks) >= 1
    h = next(iter(a._chunks))
    enc, cross, _rc = a._chunks[h]
    before = gen._tier_download([enc, cross])
    demoted = 0
    while a.demote_one():
        demoted += 1
    assert demoted >= 1 and h not in a._chunks
    assert a.stats()["host_chunks"] >= 1
    assert a.promote_chunk(h), "promote failed with free pages"
    enc2, cross2, _rc = a._chunks[h]
    after = gen._tier_download([enc2, cross2])
    assert before["kv"].tobytes() == after["kv"].tobytes(), \
        f"{gen.kv_dtype} chunk bytes changed across spill/reload"
    if before["scales"] is not None:
        assert before["scales"].tobytes() == after["scales"].tobytes(), \
            "int8 scale sidecar changed across spill/reload"
    a.check_invariants()


def test_zero_recompiles_with_tiering_active(tiered):
    """A full admit/decode/suspend/resume/demote/promote cycle after
    warmup replays compiled executables only — block tables and transfer
    feeds are int32 DATA, so tiering never widens the signature set."""
    gen, _, _ = tiered
    gen.open_slots(1)

    def cycle(sid):
        gen.admit_slot(0, PROMPT, max_new=6)
        toks = []
        _decode(gen, 0, 3, toks)
        assert gen.detach_slot(0, sid)
        gen.tier_maintenance()
        assert gen.resume_slot(0, sid) is not None
        _decode(gen, 0, 6, toks)
        gen.clear_slot(0)
        while gen.alloc.demote_one():
            pass
        gen.tier_maintenance(prefetch=PROMPT)

    cycle("warm-1")             # warm every program incl. xfer pair
    warm = gen.exe.cache_stats()["executable"]["misses"]
    cycle("warm-2")
    assert gen.exe.cache_stats()["executable"]["misses"] == warm, \
        "tiering recompiled after warmup"
    assert gen._tier_stats["prefetches"] >= 1, \
        "prefetch never promoted the queued prompt's chunks"


# -- scheduler integration ----------------------------------------------------

def test_scheduler_session_lifecycle_and_chaos(tiered):
    """Scheduler-level session flow: retire SUSPENDS (pages spill via
    the maintenance slice, not under the lock), a same-session submit
    RESUMES with exact continuation tokens, a lost artifact degrades to
    re-prefill with correct tokens, and the seeded ``kv.spill_corrupt``
    chaos point proves a torn artifact is detected (checksum), dropped,
    and ALSO degrades to re-prefill — never wrong tokens."""
    gen, store, ref = tiered
    sched = ContinuousBatchingScheduler(gen, n_slots=2,
                                        max_new_tokens=OUT)
    base = dict(gen._tier_stats)
    r1 = sched.submit(PROMPT, max_new_tokens=4, session="conv")
    sched.run_until_idle()
    assert r1.error is None and not r1.resumed
    assert r1.tokens == ref[:4]
    assert gen._tier_stats["suspends"] == base["suspends"] + 1
    assert not gen._pending_suspends, "run_until_idle left a suspend"

    r2 = sched.submit(PROMPT, max_new_tokens=6, session="conv")
    sched.run_until_idle()
    assert r2.error is None and r2.resumed
    assert r2.tokens == ref[4:10], (gen.kv_dtype, r2.tokens, ref)

    # stats schema (ISSUE 20 satellite): kv_bytes_per_token ALWAYS a
    # float; tier page counts + spill/suspend counters present
    st = sched.stats()["kv"]
    assert isinstance(st["kv_bytes_per_token"], float) \
        and st["kv_bytes_per_token"] > 0
    assert st["tiers"]["host_pages"] == 16
    assert st["spills"]["suspends"] >= 2
    assert st["spills"]["resumes"] >= 1

    # lost artifact: re-prefill, resumed False, same first tokens
    store.delete("conv")
    r3 = sched.submit(PROMPT, max_new_tokens=4, session="conv")
    sched.run_until_idle()
    assert r3.error is None and not r3.resumed
    assert r3.tokens == ref[:4]

    # torn artifact: r3's retire stored a fresh suspend; corrupt every
    # read — the checksum catches it, the session drops from both store
    # tiers, and the request decodes from the prompt instead
    corrupt0 = store.stats()["corrupt"]
    install(FaultInjector(spec="kv.spill_corrupt=1.0", seed=3))
    r4 = sched.submit(PROMPT, max_new_tokens=4, session="conv")
    sched.run_until_idle()
    install(FaultInjector())
    assert r4.error is None and not r4.resumed
    assert r4.tokens == ref[:4], "torn spill artifact produced wrong " \
        f"tokens: {r4.tokens}"
    assert store.stats()["corrupt"] == corrupt0 + 1


def test_scheduler_stats_kv_bytes_fallback():
    """A page-aware model WITHOUT the kv_bytes_per_token accessor still
    reports the key as 0.0 — the pre-fix schema emitted None and broke
    dashboard division (ISSUE 20 satellite)."""

    class _FakePaged:
        page_aware = True
        start_id, end_id = 0, 1
        page_bytes = 128
        num_pages = 8

        def open_slots(self, n):
            pass

        def lane_step(self):
            return {}

    sched = ContinuousBatchingScheduler(_FakePaged(), n_slots=1,
                                        max_new_tokens=4)
    kv = sched.stats()["kv"]
    assert kv["kv_bytes_per_token"] == 0.0
    assert isinstance(kv["kv_bytes_per_token"], float)
    assert "tiers" not in kv          # no allocator -> no tier block


# -- session store ------------------------------------------------------------

def test_session_store_integrity_semantics(tmp_path):
    """Store-level contract: bf16 arrays round-trip bitwise (raw-bytes
    framing — np.savez has no bf16), a STALE fingerprint is a miss that
    does NOT delete the artifact (a config rollback can still resume
    it), a TORN disk artifact is dropped from both tiers, host RAM is
    LRU-bounded, and idle sessions spill their RAM copy to disk-only."""
    import ml_dtypes

    store = SessionStore(dirname=str(tmp_path / "a"), host_bytes=1 << 20)
    kv = np.arange(64, dtype=np.float32).reshape(2, 32)
    bf = kv.astype(ml_dtypes.bfloat16)
    assert store.put("s", "fp-A", {"pos": 3},
                     {"kv": kv, "bf": bf})
    meta, arrays = store.get("s", "fp-A")
    assert meta["pos"] == 3
    assert arrays["kv"].tobytes() == kv.tobytes()
    assert arrays["bf"].dtype == bf.dtype
    assert arrays["bf"].tobytes() == bf.tobytes()
    # stale fingerprint: miss, artifact SURVIVES
    assert store.get("s", "fp-B") is None
    assert store.stats()["resume_misses"] == 1
    assert store.get("s", "fp-A") is not None
    # torn disk artifact: drop host copy first so get() reads disk
    store.spill_idle(0.0)
    path = store._path("s")
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[:len(raw) // 2])
    assert store.get("s", "fp-A") is None
    assert store.stats()["corrupt"] == 1
    assert not store.has("s")
    store.check_invariants()
    # host LRU: a tiny budget holds one session; disk holds both
    small = SessionStore(dirname=str(tmp_path / "b"),
                         host_bytes=kv.nbytes + 512)
    small.put("one", "fp", {}, {"kv": kv})
    small.put("two", "fp", {}, {"kv": kv})
    st = small.stats()
    assert st["host_sessions"] == 1 and st["disk_sessions"] == 2
    assert st["host_evictions"] == 1
    assert small.get("one", "fp") is not None   # promoted back from disk
    small.check_invariants()


# -- gateway ------------------------------------------------------------------

def test_gateway_session_api_and_journal_replay(tmp_path):
    """`session` rides /v1/generate's surface end to end: the blocking
    response echoes ``session``/``resumed``, the journal records the id,
    and recovery resubmits with it (a replayed request re-attaches to
    its suspended KV when the artifact survived)."""
    from paddle_tpu.serving.gateway import Gateway
    from paddle_tpu.serving.gateway.journal import RequestJournal

    store = SessionStore(dirname=str(tmp_path / "kvs"))
    gen = _mk("float32", store, prefix="tfgw")
    srcp = np.zeros((1, SRC), np.int64)
    srcp[0, :len(PROMPT)] = PROMPT
    ref = [int(t) for t in gen.greedy(srcp, [len(PROMPT)], max_new=12,
                                      stop_at_end=False)[0]]
    gw = Gateway(n_slots=2, max_new_tokens=OUT,
                 journal_path=str(tmp_path / "journal.jsonl"))
    gw.load_model("chat", "1", instance=gen, warm=False)
    gw.serve()
    try:
        o1 = gw.generate("chat", [int(t) for t in PROMPT], max_new=4,
                         session="s-1", timeout=60)
        assert o1["session"] == "s-1" and o1["resumed"] is False
        assert o1["tokens"] == ref[:4]
        deadline = time.monotonic() + 10
        while not store.has("s-1"):    # serve thread finishes the spill
            assert time.monotonic() < deadline, "suspend never completed"
            time.sleep(0.01)
        o2 = gw.generate("chat", [int(t) for t in PROMPT], max_new=6,
                         session="s-1", timeout=60)
        assert o2["resumed"] is True and o2["tokens"] == ref[4:10]
    finally:
        gw.shutdown()
    # journal carries the session id; replay hands it back to submit
    j = RequestJournal(str(tmp_path / "j2.jsonl"))
    j.record_submit("jid-1", "default", "chat", [1, 2], 4,
                    session="s-9")
    j.flush()
    entry = list(j.pending())[0]
    assert entry["session"] == "s-9"
