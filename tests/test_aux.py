"""Aux-subsystem tests: LR decay schedules, evaluators, CRF, profiler,
flags/check_nan_inf, readers/datasets, memory_optimize shim, debugger."""

import numpy as np
import pytest

from paddle_tpu import fluid
from paddle_tpu.fluid import make_seq
from paddle_tpu.utils import reader as reader_mod


def test_exponential_decay_schedule(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.layers.data(name="x", shape=[2], dtype="float32")
    p = fluid.layers.fc(input=x, size=1)
    loss = fluid.layers.mean(p)
    lr = fluid.learning_rate_decay.exponential_decay(
        learning_rate=0.1, decay_steps=10, decay_rate=0.5)
    fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.ones((2, 2), np.float32)
    lrs = []
    for _ in range(21):
        lr_v, = exe.run(main, feed={"x": xv}, fetch_list=[lr])
        lrs.append(float(np.asarray(lr_v).reshape(-1)[0]))
    # step counter increments before fetch: steps 1..21
    np.testing.assert_allclose(lrs[0], 0.1 * 0.5 ** (1 / 10), rtol=1e-5)
    np.testing.assert_allclose(lrs[20], 0.1 * 0.5 ** (21 / 10), rtol=1e-5)


def test_piecewise_decay(fresh_programs):
    main, startup, scope = fresh_programs
    lr = fluid.learning_rate_decay.piecewise_decay([3, 6], [1.0, 0.5, 0.1])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    vals = [float(np.asarray(exe.run(main, fetch_list=[lr])[0]).reshape(-1)[0])
            for _ in range(8)]
    np.testing.assert_allclose(vals[:2], 1.0, rtol=1e-6)   # steps 1,2
    np.testing.assert_allclose(vals[3], 0.5, rtol=1e-6)     # step 4 (>3)
    np.testing.assert_allclose(vals[7], 0.1, rtol=1e-6)     # step 8 (>6)


def test_accuracy_evaluator(fresh_programs):
    main, startup, scope = fresh_programs
    probs = fluid.layers.data(name="p", shape=[4], dtype="float32")
    label = fluid.layers.data(name="y", shape=[1], dtype="int64")
    ev = fluid.evaluator.Accuracy(input=probs, label=label)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    pv = np.eye(4, dtype=np.float32)               # predicts class i for row i
    exe.run(main, feed={"p": pv, "y": np.array([[0], [1], [2], [0]],
                                               np.int64)},
            fetch_list=ev.metrics)
    exe.run(main, feed={"p": pv, "y": np.array([[0], [1], [2], [3]],
                                               np.int64)},
            fetch_list=ev.metrics)
    acc = ev.eval()
    np.testing.assert_allclose(acc, 7 / 8, rtol=1e-6)
    ev.reset()
    assert ev.eval() == 0.0


def test_linear_chain_crf_trains(fresh_programs):
    main, startup, scope = fresh_programs
    emission = fluid.layers.data(name="e", shape=[5], dtype="float32",
                                 lod_level=1)
    label = fluid.layers.data(name="l", shape=[1], dtype="int64",
                              lod_level=1)
    nll = fluid.layers.linear_chain_crf(
        emission, label, param_attr=fluid.ParamAttr(name="crf_trans"))
    loss = fluid.layers.mean(nll)
    fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(40):
        seqs, lbls = [], []
        for _ in range(8):
            n = rng.randint(2, 7)
            em = 0.1 * rng.randn(n, 5).astype(np.float32)  # uninformative
            start = rng.randint(0, 5)
            lb = ((start + np.arange(n)) % 5).reshape(-1, 1)  # cyclic chain
            seqs.append(em)
            lbls.append(lb)
        feed = {"e": make_seq(seqs, np.float32, bucket=8),
                "l": make_seq(lbls, np.int32, bucket=8)}
        lv, = exe.run(main, feed=feed, fetch_list=[loss])
        losses.append(float(lv))
    # the transition matrix must learn the cycle: NLL drops markedly
    assert np.mean(losses[-5:]) < 0.7 * np.mean(losses[:5]), losses[::8]


def test_crf_decoding_matches_greedy_when_no_transitions(fresh_programs):
    main, startup, scope = fresh_programs
    emission = fluid.layers.data(name="e", shape=[4], dtype="float32",
                                 lod_level=1)
    path = fluid.layers.crf_decoding(
        emission, param_attr=fluid.ParamAttr(
            name="trans0", initializer=fluid.initializer.Constant(0.0)))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(1)
    seqs = [rng.randn(5, 4).astype(np.float32),
            rng.randn(2, 4).astype(np.float32)]
    out, = exe.run(main, feed={"e": make_seq(seqs, np.float32)},
                   fetch_list=[path], return_numpy=False)
    got = np.asarray(out.data).squeeze(-1)
    np.testing.assert_array_equal(got[0, :5], seqs[0].argmax(-1))
    np.testing.assert_array_equal(got[1, :2], seqs[1].argmax(-1))
    assert (got[1, 2:] == 0).all()


def test_check_nan_inf_flag(fresh_programs):
    from paddle_tpu.utils.flags import set_flag

    main, startup, scope = fresh_programs
    x = fluid.layers.data(name="x", shape=[2], dtype="float32")
    y = fluid.layers.log(x)  # log(-1) -> nan
    exe = fluid.Executor(fluid.CPUPlace())
    set_flag("check_nan_inf", True)
    try:
        with pytest.raises(FloatingPointError):
            exe.run(main, feed={"x": -np.ones((1, 2), np.float32)},
                    fetch_list=[y])
    finally:
        set_flag("check_nan_inf", False)


def test_profiler_table(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.layers.data(name="x", shape=[2], dtype="float32")
    y = fluid.layers.scale(x, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.profiler.profiler(print_table=False):
        for _ in range(3):
            exe.run(main, feed={"x": np.ones((1, 2), np.float32)},
                    fetch_list=[y])
        rows = fluid.profiler.get_profile_table()
    assert rows and rows[0]["calls"] == 3


def test_reader_decorators():
    base = lambda: iter(range(10))
    b = reader_mod.batch(lambda: iter(range(10)), 3)
    assert list(b()) == [[0, 1, 2], [3, 4, 5], [6, 7, 8]]
    s = reader_mod.shuffle(lambda: iter(range(10)), 5, seed=0)
    assert sorted(s()) == list(range(10))
    m = reader_mod.map_readers(lambda a: a * 2, lambda: iter(range(3)))
    assert list(m()) == [0, 2, 4]
    buf = reader_mod.buffered(lambda: iter(range(5)), 2)
    assert list(buf()) == [0, 1, 2, 3, 4]
    sh = reader_mod.shard(lambda: iter(range(10)), num_shards=2, shard_id=1)
    assert list(sh()) == [1, 3, 5, 7, 9]
    f = reader_mod.firstn(lambda: iter(range(10)), 4)
    assert list(f()) == [0, 1, 2, 3]


def test_datasets_api():
    from paddle_tpu import datasets

    img, lbl = next(datasets.mnist.train()())
    assert img.shape == (784,) and 0 <= lbl < 10
    x, y = next(datasets.uci_housing.train()())
    assert x.shape == (13,) and y.shape == (1,)
    words, sentiment = next(datasets.imdb.train()())
    assert isinstance(words, list) and sentiment in (0, 1)
    gram = next(datasets.imikolov.train(n=5)())
    assert len(gram) == 5


def test_memory_optimize_shim_and_debugger(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    h = fluid.layers.fc(input=x, size=3, act="relu")
    loss = fluid.layers.mean(h)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    n = fluid.memory_optimize(main)
    assert n >= 0
    code = fluid.debugger.pprint_program_codes(main)
    assert "mul" in code and "sgd" in code


def test_auc_evaluator_streaming(fresh_programs):
    """AUC evaluator: graph-accumulated histograms across batches match a
    direct rank-based AUC on the pooled data (gserver AucEvaluator
    parity, r3 VERDICT missing#7)."""
    main, startup, scope = fresh_programs
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data("x", [2], "float32")   # [p(neg), p(pos)]
        label = fluid.layers.data("label", [1], "int64")
        auc_ev = fluid.evaluator.AUC(input=x, label=label,
                                     num_thresholds=500)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    all_p, all_y = [], []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(5):
            y = rng.randint(0, 2, (64, 1))
            # separable-ish scores
            p = np.clip(0.35 * y + 0.3 * rng.rand(64, 1), 0, 0.999)
            probs = np.concatenate([1 - p, p], axis=1).astype(np.float32)
            exe.run(main, feed={"x": probs, "label": y.astype(np.int64)},
                    fetch_list=[])
            all_p.append(p.ravel())
            all_y.append(y.ravel())
        got = float(auc_ev.eval())
    p = np.concatenate(all_p)
    y = np.concatenate(all_y)
    # exact AUC = normalized Mann-Whitney U
    pos, neg = p[y == 1], p[y == 0]
    u = sum((pos[:, None] > neg[None, :]).sum()
            + 0.5 * (pos[:, None] == neg[None, :]).sum()
            for _ in [0])
    want = float(u) / (len(pos) * len(neg))
    assert abs(got - want) < 0.02, (got, want)

    auc_ev.reset(scope=scope)
    with fluid.scope_guard(scope):
        assert float(auc_ev.eval()) == 0.0


def test_detection_map_evaluator():
    """VOC mAP aggregation (gserver mAP evaluator parity): crafted boxes
    with known AP."""
    ev = fluid.evaluator.DetectionMAP(overlap_threshold=0.5)
    gt = [[[0, 0, 0, 10, 10]], [[0, 20, 20, 30, 30]]]
    # img0: perfect hit at score .9; img1: a miss (bad box) at .8 then a
    # hit at .7
    dets = [[[0, 0.9, 0, 0, 10, 10]],
            [[0, 0.8, 40, 40, 50, 50], [0, 0.7, 20, 20, 30, 30]]]
    ev.update(dets, gt)
    # ranked: tp, fp, tp -> prec 1, 1/2, 2/3 at rec .5, .5, 1.0
    # integral AP = 0.5*1 + 0.5*(2/3)
    got = float(ev.eval())
    assert abs(got - (0.5 + 0.5 * 2 / 3)) < 1e-6, got
    ev.reset()
    assert float(ev.eval()) == 0.0
