"""Checkpoint / IO: round-trips for every fluid.io entry point, the
host-side save/load op path, durability semantics (CRC + atomic rename,
reference go/pserver/service.go:119-175), and kill-and-restore resume
equivalence on the 8-device mesh.
"""

import os

import numpy as np
import pytest

from paddle_tpu import fluid, parallel
from paddle_tpu.fluid import SeqArray, make_seq
from paddle_tpu.fluid import io as fio
from paddle_tpu.fluid.checkpoint import CheckpointManager


# -- wire format ------------------------------------------------------------

def test_tensor_roundtrip_dtypes(tmp_path):
    import ml_dtypes

    for arr in [np.arange(12, dtype=np.float32).reshape(3, 4),
                np.arange(6, dtype=np.int64),
                np.random.RandomState(0).randn(2, 3).astype(
                    ml_dtypes.bfloat16),
                np.array(3.5, np.float32)]:
        p = str(tmp_path / "t")
        fio.save_tensor(arr, p)
        back = fio.load_tensor(p)
        assert back.dtype == arr.dtype
        np.testing.assert_array_equal(np.asarray(back), arr)


def test_seqarray_roundtrip(tmp_path):
    seq = make_seq([[1, 2, 3], [4]], dtype=np.int32, bucket=3)
    p = str(tmp_path / "s")
    fio.save_tensor(seq, p)
    back = fio.load_tensor(p)
    assert isinstance(back, SeqArray)
    np.testing.assert_array_equal(np.asarray(back.data),
                                  np.asarray(seq.data))
    np.testing.assert_array_equal(np.asarray(back.lengths),
                                  np.asarray(seq.lengths))


def test_combine_roundtrip(tmp_path):
    named = {"a": np.ones((2, 2), np.float32),
             "b": np.arange(3, dtype=np.int32),
             "s": make_seq([[7, 8]], dtype=np.int32, bucket=2)}
    p = str(tmp_path / "c")
    fio.save_tensors(named, p)
    back = fio.load_tensors(p)
    assert set(back) == set(named)
    np.testing.assert_array_equal(back["a"], named["a"])
    np.testing.assert_array_equal(np.asarray(back["s"].data),
                                  np.asarray(named["s"].data))


def test_crc_detects_corruption(tmp_path):
    p = str(tmp_path / "t")
    fio.save_tensor(np.arange(100, dtype=np.float32), p)
    raw = bytearray(open(p, "rb").read())
    raw[30] ^= 0xFF                     # flip a payload byte
    open(p, "wb").write(bytes(raw))
    with pytest.raises(fio.CheckpointCorrupt):
        fio.load_tensor(p)


def test_atomic_write_leaves_no_tmp(tmp_path):
    p = str(tmp_path / "t")
    fio.save_tensor(np.ones(4, np.float32), p)
    assert os.listdir(tmp_path) == ["t"]


# -- program-level round-trips ----------------------------------------------

def _linear_net():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data("x", [4], "float32")
        y = fluid.layers.data("y", [1], "float32")
        pred = fluid.layers.fc(input=x, size=1, param_attr="w")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    return main, startup, scope, loss, pred


def _feed(rng):
    xv = rng.randn(8, 4).astype(np.float32)
    return {"x": xv, "y": xv.sum(1, keepdims=True).astype(np.float32)}


def test_save_load_params_roundtrip(tmp_path):
    main, startup, scope, loss, _ = _linear_net()
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed=_feed(rng), fetch_list=[loss])
        fio.save_params(exe, str(tmp_path), main, scope=scope)
        w_before = np.asarray(scope.find_var("w")).copy()

    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe.run(startup)
        fio.load_params(exe, str(tmp_path), main, scope=scope2)
        np.testing.assert_array_equal(
            np.asarray(scope2.find_var("w")), w_before)


def test_resume_equals_uninterrupted(tmp_path):
    """train 6 -> save@3 -> restore into fresh scope -> 3 more == 6
    straight: optimizer state (Adam moments, beta pows) must round-trip."""
    rng_a = np.random.RandomState(7)
    feeds = [_feed(rng_a) for _ in range(6)]

    main, startup, scope, loss, _ = _linear_net()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        for i in range(3):
            exe.run(main, feed=feeds[i], fetch_list=[loss])
        fio.save_persistables(exe, str(tmp_path / "ck"), main, scope=scope)
        for i in range(3, 6):
            exe.run(main, feed=feeds[i], fetch_list=[loss])
        w_straight = np.asarray(scope.find_var("w")).copy()

    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe.run(startup)
        fio.load_persistables(exe, str(tmp_path / "ck"), main, scope=scope2)
        for i in range(3, 6):
            exe.run(main, feed=feeds[i], fetch_list=[loss])
        w_resumed = np.asarray(scope2.find_var("w"))
    np.testing.assert_array_equal(w_resumed, w_straight)


def test_host_save_load_op_path(tmp_path):
    """The reference checkpoints by RUNNING save/load ops
    (operators/save_op.cc) — drive that exact path."""
    from paddle_tpu.fluid.core.desc import OpDesc

    main, startup, scope, loss, _ = _linear_net()
    p = str(tmp_path / "w_file")
    save_prog = fluid.Program()
    save_prog.global_block().desc.append_op(
        OpDesc("save", {"X": ["w"]}, {}, {"file_path": p}))
    load_prog = fluid.Program()
    load_prog.global_block().desc.append_op(
        OpDesc("load", {}, {"Out": ["w"]}, {"file_path": p}))

    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(1)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_feed(rng), fetch_list=[loss])
        w = np.asarray(scope.find_var("w")).copy()
        exe.run(save_prog)
        assert os.path.exists(p)
        # clobber then restore through the load op
        scope.set_var("w", np.zeros_like(w))
        exe.run(load_prog)
        np.testing.assert_array_equal(np.asarray(scope.find_var("w")), w)


def test_inference_model_roundtrip(tmp_path):
    main, startup, scope, loss, pred = _linear_net()
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(2)
    feed = _feed(rng)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])
        fio.save_inference_model(str(tmp_path / "m"), ["x"], [pred], exe,
                                 main, scope=scope)
        want, = exe.run(main, feed=feed, fetch_list=[pred])

    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        prog, feed_names, fetch_vars = fio.load_inference_model(
            str(tmp_path / "m"), exe, scope=scope2)
        assert feed_names == ["x"]
        got, = exe.run(prog, feed={"x": feed["x"]},
                       fetch_list=fetch_vars)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-6)


# -- CheckpointManager durability -------------------------------------------

def test_checkpoint_manager_periodic_and_prune(tmp_path):
    main, startup, scope, loss, _ = _linear_net()
    exe = fluid.Executor(fluid.CPUPlace())
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2,
                            save_interval_steps=2)
    rng = np.random.RandomState(3)
    with fluid.scope_guard(scope):
        exe.run(startup)
        for step in range(1, 7):
            exe.run(main, feed=_feed(rng), fetch_list=[loss])
            mgr.save(step, main, scope)
    # steps 2,4,6 saved; keep=2 -> 4,6 remain
    kept = sorted(d for d in os.listdir(tmp_path) if d.startswith("ckpt-"))
    assert kept == ["ckpt-4", "ckpt-6"]
    assert mgr.latest_step() == 6


def test_checkpoint_manager_restore_skips_corrupt(tmp_path):
    main, startup, scope, loss, _ = _linear_net()
    exe = fluid.Executor(fluid.CPUPlace())
    mgr = CheckpointManager(str(tmp_path), max_to_keep=3)
    rng = np.random.RandomState(4)
    w_at = {}
    with fluid.scope_guard(scope):
        exe.run(startup)
        for step in (1, 2):
            exe.run(main, feed=_feed(rng), fetch_list=[loss])
            mgr.save(step, main, scope)
            w_at[step] = np.asarray(scope.find_var("w")).copy()
    # corrupt the newest checkpoint's tensor file
    f = os.path.join(tmp_path, "ckpt-2", "w")
    raw = bytearray(open(f, "rb").read())
    raw[-10] ^= 0xFF
    open(f, "wb").write(bytes(raw))

    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe.run(startup)
        step = mgr.restore(main, scope2)
    assert step == 1                      # fell back past the corrupt one
    np.testing.assert_array_equal(np.asarray(scope2.find_var("w")),
                                  w_at[1])


def test_checkpoint_manager_restore_falls_back_on_truncation(tmp_path):
    """A TRUNCATED newest checkpoint (torn write: the file ends
    mid-payload, CRC trailer gone) must not stop restore() — it falls
    back to the previous CRC-valid checkpoint, like pserver's
    LoadCheckpoint scan."""
    main, startup, scope, loss, _ = _linear_net()
    exe = fluid.Executor(fluid.CPUPlace())
    mgr = CheckpointManager(str(tmp_path), max_to_keep=3)
    rng = np.random.RandomState(8)
    w_at = {}
    with fluid.scope_guard(scope):
        exe.run(startup)
        for step in (1, 2):
            exe.run(main, feed=_feed(rng), fetch_list=[loss])
            mgr.save(step, main, scope)
            w_at[step] = np.asarray(scope.find_var("w")).copy()
    f = os.path.join(tmp_path, "ckpt-2", "w")
    size = os.path.getsize(f)
    with open(f, "r+b") as fh:
        fh.truncate(size // 2)

    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe.run(startup)
        step = mgr.restore(main, scope2)
    assert step == 1
    np.testing.assert_array_equal(np.asarray(scope2.find_var("w")),
                                  w_at[1])


def test_kill_and_restore_on_mesh():
    """Train under the dp mesh, checkpoint, 'kill' (fresh scope), restore,
    resume — final params bit-match the uninterrupted run."""
    import tempfile, jax

    mesh = parallel.make_mesh({"dp": 8})
    rng_feed = np.random.RandomState(9)
    feeds = [_feed(rng_feed) for _ in range(6)]

    with tempfile.TemporaryDirectory() as d:
        main, startup, scope, loss, _ = _linear_net()
        exe = fluid.Executor(fluid.CPUPlace())
        mgr = CheckpointManager(d)
        with parallel.mesh_guard(mesh), fluid.scope_guard(scope):
            exe.run(startup)
            for i in range(3):
                exe.run(main, feed=feeds[i], fetch_list=[loss])
            mgr.save(3, main, scope)
            for i in range(3, 6):
                exe.run(main, feed=feeds[i], fetch_list=[loss])
            w_straight = np.asarray(scope.find_var("w")).copy()

        # simulated crash: everything in-memory is gone
        scope2 = fluid.Scope()
        with parallel.mesh_guard(mesh), fluid.scope_guard(scope2):
            exe2 = fluid.Executor(fluid.CPUPlace())
            exe2.run(startup)
            step = mgr.restore(main, scope2)
            assert step == 3
            for i in range(3, 6):
                exe2.run(main, feed=feeds[i], fetch_list=[loss])
            w_resumed = np.asarray(scope2.find_var("w"))
    np.testing.assert_array_equal(w_resumed, w_straight)


def test_checkpoint_bf16_params(tmp_path):
    """bf16 persistables survive the wire format."""
    import ml_dtypes, jax.numpy as jnp

    scope = fluid.Scope()
    w = jnp.asarray(np.random.RandomState(0).randn(4, 4),
                    dtype=jnp.bfloat16)
    scope.set_var("wb", w)
    fio.save_tensor(scope.find_var("wb"), str(tmp_path / "wb"))
    back = fio.load_tensor(str(tmp_path / "wb"))
    assert np.asarray(back).dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(np.asarray(back), np.asarray(w))


def test_restore_prefers_newest_over_stale_marker(tmp_path):
    """A crash between checkpoint publish and marker write must not make
    restore() pick the older checkpoint (r2 review finding)."""
    main, startup, scope, loss, _ = _linear_net()
    exe = fluid.Executor(fluid.CPUPlace())
    mgr = CheckpointManager(str(tmp_path), max_to_keep=3)
    rng = np.random.RandomState(5)
    w_at = {}
    with fluid.scope_guard(scope):
        exe.run(startup)
        for step in (1, 2):
            exe.run(main, feed=_feed(rng), fetch_list=[loss])
            mgr.save(step, main, scope)
            w_at[step] = np.asarray(scope.find_var("w")).copy()
    # simulate the crash: roll the marker back to 1 (ckpt-2 is valid)
    with open(os.path.join(tmp_path, "latest"), "w") as f:
        f.write("1")
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe.run(startup)
        assert mgr.restore(main, scope2) == 2
    np.testing.assert_array_equal(np.asarray(scope2.find_var("w")),
                                  w_at[2])


def test_orphaned_tmp_dirs_are_collected(tmp_path):
    main, startup, scope, loss, _ = _linear_net()
    exe = fluid.Executor(fluid.CPUPlace())
    mgr = CheckpointManager(str(tmp_path))
    # orphan from a "crashed" save by some other process
    os.makedirs(os.path.join(tmp_path, "ckpt-9.12345.tmp"))
    rng = np.random.RandomState(6)
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=_feed(rng), fetch_list=[loss])
        mgr.save(1, main, scope)
    assert not any(n.endswith(".tmp") for n in os.listdir(tmp_path))
