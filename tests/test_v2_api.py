"""v2 API layer: reference-shaped scripts (paddle.init / layer DSL /
trainer.SGD(train(reader=..., event_handler=...)) / parameters tar /
infer) running on the fluid/XLA engine — VERDICT r1 #6's contract:
fit_a_line and MNIST v2-style scripts train with an import swap.
"""

import io as pyio

import numpy as np

import paddle_tpu.v2 as paddle


def _housing_reader(rng, n=64):
    w = np.arange(1, 14, dtype=np.float32) / 13.0

    def reader():
        for _ in range(n):
            x = rng.randn(13).astype(np.float32)
            y = np.array([x @ w], np.float32)
            yield x, y

    return reader


def test_v2_fit_a_line_trains_and_infers():
    paddle.init(use_gpu=False, trainer_count=1, seed=7)
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(13))
    y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(1))
    y_predict = paddle.layer.fc(input=x, size=1,
                                act=paddle.activation.Linear())
    cost = paddle.layer.mse_cost(input=y_predict, label=y)

    parameters = paddle.parameters.create(cost)
    optimizer = paddle.optimizer.Momentum(momentum=0.9, learning_rate=2e-2)
    trainer = paddle.trainer.SGD(cost=cost, parameters=parameters,
                                 update_equation=optimizer)

    events = {"costs": [], "passes": []}

    def event_handler(event):
        if isinstance(event, paddle.event.EndIteration):
            events["costs"].append(event.cost)
        elif isinstance(event, paddle.event.EndPass):
            events["passes"].append(event.pass_id)

    rng = np.random.RandomState(0)
    trainer.train(reader=paddle.batch(_housing_reader(rng), batch_size=16),
                  num_passes=6, event_handler=event_handler,
                  feeding={"x": 0, "y": 1})
    assert events["passes"] == list(range(6))
    assert events["costs"][-1] < events["costs"][0] * 0.3, \
        events["costs"][::8]

    # test() runs the inference clone
    result = trainer.test(reader=paddle.batch(_housing_reader(rng, 32), 16),
                          feeding={"x": 0, "y": 1})
    assert np.isfinite(result.cost)

    # parameters round-trip through the v2 tar format
    buf = pyio.BytesIO()
    trainer.save_parameter_to_tar(buf)
    buf.seek(0)
    w_before = parameters["fc_0.w_0"] if "fc_0.w_0" in parameters.names() \
        else parameters[parameters.names()[0]]
    parameters.set(parameters.names()[0],
                   np.zeros_like(w_before))
    parameters.from_tar(buf)
    np.testing.assert_array_equal(parameters[parameters.names()[0]],
                                  w_before)

    # infer matches a manual forward
    batch_rows = [(np.ones(13, np.float32) * 0.1,)]
    probs = paddle.infer(output_layer=y_predict, parameters=parameters,
                         input=batch_rows, feeding={"x": 0})
    assert probs.shape == (1, 1) and np.isfinite(probs).all()


def test_v2_mnist_mlp_trains():
    paddle.init(use_gpu=False, trainer_count=1, seed=11)
    images = paddle.layer.data(name="pixel",
                               type=paddle.data_type.dense_vector(64))
    label = paddle.layer.data(name="label",
                              type=paddle.data_type.integer_value(10))
    h1 = paddle.layer.fc(input=images, size=32,
                         act=paddle.activation.Relu())
    predict = paddle.layer.fc(input=h1, size=10,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=label)

    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3))

    rng = np.random.RandomState(1)

    def reader():
        # synthetic digits: class k = bright k-th row of an 8x8 image
        for _ in range(96):
            k = rng.randint(0, 10)
            img = rng.rand(64).astype(np.float32) * 0.1
            img[(k % 8) * 8: (k % 8) * 8 + 8] += 1.0
            img[k % 64] += float(k) / 10.0
            yield img, int(k)

    costs = []

    def handler(event):
        if isinstance(event, paddle.event.EndIteration):
            costs.append(event.cost)

    trainer.train(reader=paddle.batch(reader, batch_size=32),
                  num_passes=8, event_handler=handler)
    assert costs[-1] < costs[0] * 0.7, costs[::8]

    # infer returns class probabilities for raw rows
    rows = [(np.ones(64, np.float32) * 0.2,)]
    probs = paddle.infer(output_layer=predict, parameters=parameters,
                         input=rows, feeding={"pixel": 0})
    assert probs.shape == (1, 10)
    np.testing.assert_allclose(probs.sum(), 1.0, rtol=1e-4)


def test_v2_sequence_classification():
    """sequence data types flow through the v2 feeder (SeqArray)."""
    paddle.init(seed=3)
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(30))
    label = paddle.layer.data(name="label",
                              type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=words, size=8)
    pooled = paddle.layer.pool(input=emb, pool_type=paddle.pooling.Max)
    predict = paddle.layer.fc(input=pooled, size=2,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=label)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2))

    rng = np.random.RandomState(5)

    def reader():
        for _ in range(64):
            pos = rng.randint(0, 2)
            lo, hi = (0, 15) if pos == 0 else (15, 30)
            yield rng.randint(lo, hi, rng.randint(2, 7)).tolist(), pos

    costs = []
    trainer.train(
        reader=paddle.batch(reader, batch_size=16), num_passes=6,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0], (costs[0], costs[-1])


def test_v2_test_does_not_train():
    """r2 review: trainer.test() must be forward-only — evaluating on a
    reader cannot move parameters."""
    paddle.init(seed=13)
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1,
                           act=paddle.activation.Linear())
    cost = paddle.layer.mse_cost(input=pred, label=y)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.1))
    rng = np.random.RandomState(2)

    def reader():
        for _ in range(8):
            xv = rng.randn(4).astype(np.float32)
            yield xv, np.array([xv.sum()], np.float32)

    trainer.train(reader=paddle.batch(reader, 4), num_passes=1)
    name = params.names()[0]
    before = params[name].copy()
    trainer.test(reader=paddle.batch(reader, 4))
    np.testing.assert_array_equal(params[name], before)


def test_v2_partial_batch_yields():
    """r2 review: v2 batch keeps the trailing partial batch (reference
    minibatch contract); 5 rows @ batch 4 -> 2 batches."""
    rows = [(np.zeros(2, np.float32),)] * 5
    batches = list(paddle.batch(lambda: iter(rows), 4)())
    assert [len(b) for b in batches] == [4, 1]


def test_v2_embedding_requires_int_data_layer():
    import pytest

    paddle.init()
    x = paddle.layer.data(name="xf", type=paddle.data_type.dense_vector(4))
    with pytest.raises(ValueError, match="integer data layer"):
        paddle.layer.embedding(input=x, size=8)


# ---------------------------------------------------------------------------
# r3: recurrent DSL (VERDICT r2 next#4) — sentiment-LSTM and seq2seq
# v2-style scripts train through SGD.train with an import swap.
# ---------------------------------------------------------------------------

def _seq_cls_reader(rng, vocab, n=48, max_len=6):
    """Synthetic 'sentiment': label = whether ids are mostly high.
    Fixed dataset (generated once) so multi-pass training converges."""
    data = []
    for _ in range(n):
        ln = rng.randint(2, max_len + 1)
        ids = rng.randint(0, vocab, ln)
        data.append((ids.tolist(), int(ids.mean() > vocab / 2)))

    def reader():
        yield from data

    return reader


def test_v2_sentiment_lstm_trains():
    """understand_sentiment-style v2 script: embedding -> simple_lstm ->
    seq pool -> softmax fc (reference book ch.06 / networks.simple_lstm)."""
    vocab = 30
    paddle.init(seed=11)
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(vocab))
    label = paddle.layer.data(
        name="label", type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=words, size=16)
    lstm_h = paddle.networks.simple_lstm(input=emb, size=16)
    pooled = paddle.layer.pool(input=lstm_h,
                               pool_type=paddle.pooling.Max())
    pred = paddle.layer.fc(input=pooled, size=2,
                           act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=pred, label=label)

    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3))
    costs = []

    def handler(ev):
        if isinstance(ev, paddle.event.EndIteration):
            costs.append(ev.cost)

    rng = np.random.RandomState(0)
    trainer.train(reader=paddle.batch(_seq_cls_reader(rng, vocab), 16),
                  num_passes=16, event_handler=handler,
                  feeding={"words": 0, "label": 1})
    assert np.mean(costs[-3:]) < costs[0] * 0.8, costs[::6]


def test_v2_recurrent_group_memory_fc():
    """Vanilla-RNN via recurrent_group + name-linked memory (reference
    layers.py memory/recurrent_group pattern): the fc named 'rnn_state'
    updates the memory that reads it one step back."""
    vocab, hidden = 20, 12
    paddle.init(seed=5)
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(vocab))
    label = paddle.layer.data(
        name="label", type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=words, size=8)

    def step(y):
        mem = paddle.layer.memory(name="rnn_state", size=hidden)
        return paddle.layer.fc(input=[y, mem], size=hidden,
                               act=paddle.activation.Tanh(),
                               name="rnn_state")

    rnn_out = paddle.layer.recurrent_group(step=step, input=emb)
    last = paddle.layer.last_seq(rnn_out)
    pred = paddle.layer.fc(input=last, size=2,
                           act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=pred, label=label)

    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3))
    costs = []
    rng = np.random.RandomState(1)
    trainer.train(
        reader=paddle.batch(_seq_cls_reader(rng, vocab), 16),
        num_passes=6,
        event_handler=lambda ev: costs.append(ev.cost)
        if isinstance(ev, paddle.event.EndIteration) else None,
        feeding={"words": 0, "label": 1})
    assert costs[-1] < costs[0], costs[::6]


def test_v2_seq2seq_encoder_decoder_trains():
    """machine_translation-style v2 script: GRU encoder, decoder
    recurrent_group with encoder context as StaticInput + boot-from-
    encoder memory, per-step softmax over the target vocab."""
    src_vocab, trg_vocab, hidden = 16, 18, 10
    paddle.init(seed=9)
    src = paddle.layer.data(
        name="src", type=paddle.data_type.integer_value_sequence(src_vocab))
    trg = paddle.layer.data(
        name="trg", type=paddle.data_type.integer_value_sequence(trg_vocab))
    trg_next = paddle.layer.data(
        name="trg_next",
        type=paddle.data_type.integer_value_sequence(trg_vocab))

    src_emb = paddle.layer.embedding(input=src, size=8)
    enc = paddle.networks.simple_gru(input=src_emb, size=hidden)
    enc_last = paddle.layer.last_seq(enc)

    trg_emb = paddle.layer.embedding(input=trg, size=8)

    def decoder_step(cur_word, enc_ctx):
        mem = paddle.layer.memory(name="dec_state", size=hidden,
                                  boot_layer=enc_last)
        out = paddle.layer.fc(input=[cur_word, mem, enc_ctx],
                              size=hidden, act=paddle.activation.Tanh(),
                              name="dec_state")
        return out

    dec = paddle.layer.recurrent_group(
        step=decoder_step,
        input=[trg_emb, paddle.layer.StaticInput(enc_last)])
    pred = paddle.layer.fc(input=dec, size=trg_vocab,
                           act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=pred, label=trg_next)

    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3))

    def reader():
        rng = np.random.RandomState(2)
        for _ in range(32):
            ln = rng.randint(2, 5)
            s = rng.randint(0, src_vocab, ln).tolist()
            # toy copy-ish task: target mirrors source mod trg_vocab
            t = [x % trg_vocab for x in s]
            yield s, t, t[1:] + [0]

    costs = []
    trainer.train(
        reader=paddle.batch(reader, 8), num_passes=6,
        event_handler=lambda ev: costs.append(ev.cost)
        if isinstance(ev, paddle.event.EndIteration) else None,
        feeding={"src": 0, "trg": 1, "trg_next": 2})
    assert costs[-1] < costs[0], costs[::8]


def test_v2_bidirectional_lstm():
    vocab = 24
    paddle.init(seed=3)
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(vocab))
    label = paddle.layer.data(
        name="label", type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=words, size=8)
    bi = paddle.networks.bidirectional_lstm(input=emb, size=6)
    pred = paddle.layer.fc(input=bi, size=2,
                           act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=pred, label=label)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3))
    costs = []
    rng = np.random.RandomState(4)
    trainer.train(
        reader=paddle.batch(_seq_cls_reader(rng, vocab), 12),
        num_passes=4,
        event_handler=lambda ev: costs.append(ev.cost)
        if isinstance(ev, paddle.event.EndIteration) else None,
        feeding={"words": 0, "label": 1})
    assert costs[-1] < costs[0], costs


def test_v2_beam_search_generation():
    """Generation-mode recurrent step via paddle.layer.beam_search
    (reference layers.py beam_search / GeneratedInput): the trained
    decoder step generates sequences with beam expansion + decode."""
    src_vocab, trg_vocab, hidden, emb_dim = 14, 15, 10, 8
    BOS, EOS = 0, 1
    paddle.init(seed=13)
    src = paddle.layer.data(
        name="src", type=paddle.data_type.integer_value_sequence(src_vocab))
    trg = paddle.layer.data(
        name="trg", type=paddle.data_type.integer_value_sequence(trg_vocab))
    trg_next = paddle.layer.data(
        name="trg_next",
        type=paddle.data_type.integer_value_sequence(trg_vocab))

    src_emb = paddle.layer.embedding(input=src, size=emb_dim)
    enc = paddle.networks.simple_gru(input=src_emb, size=hidden)
    enc_last = paddle.layer.last_seq(enc)

    dec_fc = paddle.attr.Param(name="gen_dec_fc_w")
    dec_fc_b = paddle.attr.Param(name="gen_dec_fc_b")
    out_fc = paddle.attr.Param(name="gen_out_fc_w")
    out_fc_b = paddle.attr.Param(name="gen_out_fc_b")

    def decoder_step(cur_word, enc_ctx):
        mem = paddle.layer.memory(name="gen_state", size=hidden,
                                  boot_layer=enc_last)
        merged = paddle.layer.concat([cur_word, mem, enc_ctx])
        h = paddle.layer.fc(input=merged, size=hidden,
                            act=paddle.activation.Tanh(),
                            name="gen_state", param_attr=dec_fc,
                            bias_attr=dec_fc_b)
        score = paddle.layer.fc(input=h, size=trg_vocab,
                                act=paddle.activation.Softmax(),
                                param_attr=out_fc, bias_attr=out_fc_b)
        return h, score

    # training tower: teacher forcing through the SAME step function
    trg_emb = paddle.layer.embedding(
        input=trg, size=emb_dim,
        param_attr=paddle.attr.Param(name="trg_emb_w"))
    _, score_seq = paddle.layer.recurrent_group(
        step=decoder_step,
        input=[trg_emb, paddle.layer.StaticInput(enc_last)])
    cost = paddle.layer.classification_cost(input=score_seq,
                                            label=trg_next)

    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3))

    def reader():
        rng = np.random.RandomState(6)
        for _ in range(24):
            ln = rng.randint(2, 5)
            s = rng.randint(2, src_vocab, ln).tolist()
            t = [x % (trg_vocab - 2) + 2 for x in s]
            yield s, [BOS] + t, t + [EOS]

    costs = []
    trainer.train(
        reader=paddle.batch(reader, 8), num_passes=4,
        event_handler=lambda ev: costs.append(ev.cost)
        if isinstance(ev, paddle.event.EndIteration) else None,
        feeding={"src": 0, "trg": 1, "trg_next": 2})
    assert costs[-1] < costs[0], costs

    # generation tower: same step fn + shared params, beam expansion
    beam_ids, beam_scores = paddle.layer.beam_search(
        step=decoder_step,
        input=[paddle.layer.GeneratedInput(
                   size=trg_vocab, embedding_name="trg_emb_w",
                   embedding_size=emb_dim),
               paddle.layer.StaticInput(enc_last)],
        bos_id=BOS, eos_id=EOS, beam_size=3, max_length=6)

    inferer = paddle.inference.Inference(
        output_layer=[beam_ids, beam_scores], parameters=parameters)
    rows = [([3, 5, 2],), ([4, 2, 6, 7],)]
    ids_out, scores_out = inferer.infer(input=rows, feeding={"src": 0})
    ids_out = np.asarray(ids_out)
    scores_out = np.asarray(scores_out)
    assert ids_out.shape[0] == 2 and ids_out.shape[1] == 3  # [B, W, T]
    assert np.isfinite(scores_out).all()
    # every hypothesis is made of target-vocab ids
    assert ((ids_out >= 0) & (ids_out < trg_vocab)).all()


def test_v2_addto_cos_sim_bigru():
    """r3 alias batch: addto (ResNet-style join), cos_sim, seq_concat,
    bidirectional_gru all build and train."""
    paddle.init(seed=21)
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(20))
    label = paddle.layer.data(
        name="label", type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=words, size=8)
    emb2 = paddle.layer.embedding(input=words, size=8)
    joined = paddle.layer.addto([emb, emb2],
                                act=paddle.activation.Relu())
    both = paddle.layer.seq_concat(joined, emb)
    bi = paddle.networks.bidirectional_gru(input=both, size=6)
    pred = paddle.layer.fc(input=bi, size=2,
                           act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=pred, label=label)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3))
    rng = np.random.RandomState(8)
    costs = []
    trainer.train(
        reader=paddle.batch(_seq_cls_reader(rng, 20, n=32), 8),
        num_passes=3,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
        feeding={"words": 0, "label": 1})
    assert np.isfinite(costs).all() and costs[-1] < costs[0] * 1.2

    # cos_sim on two dense layers
    paddle.init(seed=22)
    a = paddle.layer.data(name="a", type=paddle.data_type.dense_vector(6))
    b = paddle.layer.data(name="b", type=paddle.data_type.dense_vector(6))
    sim = paddle.layer.cos_sim(a, b, scale=2.0)
    params2 = paddle.parameters.create(
        paddle.layer.mse_cost(input=sim, label=paddle.layer.data(
            name="t", type=paddle.data_type.dense_vector(1))))
    out = paddle.infer(output_layer=sim, parameters=params2,
                       input=[(np.ones(6, np.float32),
                               np.ones(6, np.float32))],
                       feeding={"a": 0, "b": 1})
    np.testing.assert_allclose(np.asarray(out).ravel()[0], 2.0, rtol=1e-5)


def test_v2_beam_search_unnamed_params_raise():
    """r3 VERDICT weak#5: a step function whose layers mint parameters
    without explicit ParamAttr names would generate from UNTRAINED weights
    (each re-trace makes fresh uniquely-named copies) — that foot-gun is
    now a loud error, not silent wrong output."""
    src_vocab, trg_vocab, hidden, emb_dim = 10, 11, 6, 4
    paddle.init(seed=5)
    src = paddle.layer.data(
        name="src", type=paddle.data_type.integer_value_sequence(src_vocab))
    src_emb = paddle.layer.embedding(input=src, size=emb_dim)
    enc_last = paddle.layer.last_seq(
        paddle.networks.simple_gru(input=src_emb, size=hidden))

    def bad_step(cur_word, enc_ctx):
        mem = paddle.layer.memory(name="bad_state", size=hidden,
                                  boot_layer=enc_last)
        merged = paddle.layer.concat([cur_word, mem, enc_ctx])
        h = paddle.layer.fc(input=merged, size=hidden,
                            act=paddle.activation.Tanh(),
                            name="bad_state")      # <- no param_attr name
        score = paddle.layer.fc(input=h, size=trg_vocab,
                                act=paddle.activation.Softmax())
        return h, score

    import pytest
    with pytest.raises(ValueError, match="explicit"):
        paddle.layer.beam_search(
            step=lambda w, c: bad_step(w, c)[1],
            input=[paddle.layer.GeneratedInput(
                size=trg_vocab, embedding_name="bad_emb_w",
                embedding_size=emb_dim),
                paddle.layer.StaticInput(input=enc_last)],
            bos_id=0, eos_id=1, beam_size=3, max_length=4)


def test_v2_srl_crf_trains():
    """A v2-style SRL pipeline (reference demo/semantic_role_labeling:
    embedding -> context window -> fc emission -> CRF cost) trains via
    SGD.train, and crf_decoding shares the trained transitions by
    parameter name (r3 VERDICT missing#5)."""
    vocab, n_tags, emb_dim = 20, 5, 8
    paddle.init(seed=11)
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(vocab))
    tags = paddle.layer.data(
        name="tags", type=paddle.data_type.integer_value_sequence(n_tags))
    emb = paddle.layer.embedding(input=words, size=emb_dim)
    ctxp = paddle.layer.context_projection(emb, context_len=3)
    emission = paddle.layer.fc(input=ctxp, size=n_tags)
    crf_attr = paddle.attr.Param(name="srl_crf_w")
    cost = paddle.layer.crf(input=emission, label=tags, size=n_tags,
                            param_attr=crf_attr)
    decoded = paddle.layer.crf_decoding(input=emission, size=n_tags,
                                        param_attr=crf_attr)
    assert decoded is not None

    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=2e-2))

    rng = np.random.RandomState(3)

    def reader():
        for _ in range(24):
            n = rng.randint(3, 7)
            w = rng.randint(0, vocab, n)
            # learnable mapping: tag follows the word id mod n_tags
            t = w % n_tags
            yield w.tolist(), t.tolist()

    costs = []
    trainer.train(
        reader=paddle.batch(reader, 8), num_passes=8,
        event_handler=lambda ev: costs.append(ev.cost)
        if isinstance(ev, paddle.event.EndIteration) else None,
        feeding={"words": 0, "tags": 1})
    assert costs[-1] < costs[0] * 0.8, (costs[0], costs[-1])


def test_v2_ctc_trains():
    """ctc_layer analog: softmax-free acoustic scores + unaligned label
    sequence train through warp-ctc via SGD.train."""
    vocab, n_cls = 12, 6            # classes incl. blank 0
    paddle.init(seed=17)
    feats = paddle.layer.data(
        name="feats", type=paddle.data_type.integer_value_sequence(vocab))
    labels = paddle.layer.data(
        name="labels", type=paddle.data_type.integer_value_sequence(n_cls))
    emb = paddle.layer.embedding(input=feats, size=8)
    scores = paddle.layer.fc(input=emb, size=n_cls)
    cost = paddle.layer.ctc(input=scores, label=labels, size=n_cls,
                            blank=0)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=2e-2))
    rng = np.random.RandomState(5)

    def reader():
        for _ in range(16):
            n = rng.randint(4, 8)
            w = rng.randint(0, vocab, n)
            lab = (w[: max(1, n // 2)] % (n_cls - 1)) + 1   # no blanks
            yield w.tolist(), lab.tolist()

    costs = []
    trainer.train(
        reader=paddle.batch(reader, 8), num_passes=6,
        event_handler=lambda ev: costs.append(ev.cost)
        if isinstance(ev, paddle.event.EndIteration) else None,
        feeding={"feats": 0, "labels": 1})
    assert costs[-1] < costs[0], (costs[0], costs[-1])


def test_v2_maxout_conv_projection():
    """maxout_layer + conv_projection wrappers match their fluid ops."""
    paddle.init(seed=2)
    img = paddle.layer.data(
        name="img", type=paddle.data_type.dense_vector(4 * 8 * 8))
    from paddle_tpu import fluid

    r = fluid.layers.reshape(img, [-1, 4, 8, 8])
    proj = paddle.layer.conv_projection(r, filter_size=3, num_filters=4,
                                        padding=1)
    mo = paddle.layer.maxout(proj, groups=2)
    label = paddle.layer.data(name="label",
                              type=paddle.data_type.integer_value(2))
    pred = paddle.layer.fc(input=mo, size=2,
                           act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=pred, label=label)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2))
    rng = np.random.RandomState(8)

    def reader():
        for _ in range(12):
            x = rng.rand(64).astype(np.float32)
            yield np.tile(x, 4), int(x.mean() > 0.5)

    costs = []
    trainer.train(
        reader=paddle.batch(reader, 6), num_passes=4,
        event_handler=lambda ev: costs.append(ev.cost)
        if isinstance(ev, paddle.event.EndIteration) else None,
        feeding={"img": 0, "label": 1})
    assert np.isfinite(costs).all() and costs[-1] < costs[0]


def test_v2_attention_seq2seq_trains():
    """Attention seq2seq in the reference demo shape (networks.py
    simple_attention inside the decoder's recurrent_group over
    StaticInput encoder outputs) trains via SGD.train."""
    src_vocab, trg_vocab, hidden, emb_dim = 12, 13, 8, 6
    paddle.init(seed=23)
    src = paddle.layer.data(
        name="src", type=paddle.data_type.integer_value_sequence(src_vocab))
    trg = paddle.layer.data(
        name="trg", type=paddle.data_type.integer_value_sequence(trg_vocab))
    trg_next = paddle.layer.data(
        name="trg_next",
        type=paddle.data_type.integer_value_sequence(trg_vocab))

    src_emb = paddle.layer.embedding(input=src, size=emb_dim)
    enc = paddle.networks.simple_gru(input=src_emb, size=hidden)
    enc_proj = paddle.layer.fc(input=enc, size=hidden, bias_attr=False)
    enc_last = paddle.layer.last_seq(enc)

    trg_emb = paddle.layer.embedding(input=trg, size=emb_dim)

    def decoder_step(cur_word, enc_seq, enc_proj_s):
        state = paddle.layer.memory(name="att_state", size=hidden,
                                    boot_layer=enc_last)
        context = paddle.layer.simple_attention(
            encoded_sequence=enc_seq, encoded_proj=enc_proj_s,
            decoder_state=state)
        merged = paddle.layer.concat([cur_word, context, state])
        h = paddle.layer.fc(input=merged, size=hidden,
                            act=paddle.activation.Tanh(),
                            name="att_state")
        return paddle.layer.fc(input=h, size=trg_vocab,
                               act=paddle.activation.Softmax())

    dec_out = paddle.layer.recurrent_group(
        step=decoder_step,
        input=[trg_emb,
               paddle.layer.StaticInput(input=enc, is_seq=True),
               paddle.layer.StaticInput(input=enc_proj, is_seq=True)])
    cost = paddle.layer.classification_cost(input=dec_out, label=trg_next)

    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3))
    rng = np.random.RandomState(31)

    def reader():
        for _ in range(24):
            n = rng.randint(2, 5)
            s = rng.randint(0, src_vocab, n)
            t = s % trg_vocab
            yield s.tolist(), t.tolist(), np.roll(t, -1).tolist()

    costs = []
    trainer.train(
        reader=paddle.batch(reader, 8), num_passes=6,
        event_handler=lambda ev: costs.append(ev.cost)
        if isinstance(ev, paddle.event.EndIteration) else None,
        feeding={"src": 0, "trg": 1, "trg_next": 2})
    assert costs[-1] < costs[0], (costs[0], costs[-1])


def test_v2_text_conv_pool_and_dot_attention():
    """networks.py tail: text_conv_pool classifier + dot_product_attention
    seq2seq both train via SGD.train."""
    vocab = 18
    paddle.init(seed=7)
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(vocab))
    label = paddle.layer.data(name="label",
                              type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=words, size=8)
    pooled = paddle.networks.text_conv_pool(emb, context_len=3,
                                            hidden_size=12)
    pred = paddle.layer.fc(input=pooled, size=2,
                           act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=pred, label=label)

    # dot-product attention context has attended width; multi-head concat
    # has value_proj_size — checked on EXECUTED values (static shapes drop
    # through the pooled chain).  Built BEFORE parameters.create so the
    # attention projections get initialized too.
    q = paddle.layer.fc(input=pooled, size=6)
    ctx = paddle.networks.dot_product_attention(
        encoded_sequence=paddle.layer.fc(input=emb, size=6,
                                         bias_attr=False),
        attended_sequence=paddle.layer.fc(input=emb, size=10,
                                          bias_attr=False),
        transformed_state=q)
    mh = paddle.networks.multi_head_attention(
        query=q, key=emb, value=emb, key_proj_size=12, value_proj_size=8,
        head_num=2)

    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2))
    rng = np.random.RandomState(9)
    trainer.train(
        reader=paddle.batch(_seq_cls_reader(rng, vocab), 8), num_passes=4,
        feeding={"words": 0, "label": 1})

    from paddle_tpu import fluid
    from paddle_tpu.fluid import make_seq

    exe = fluid.Executor(fluid.CPUPlace())
    rng2 = np.random.RandomState(1)
    seqs = [rng2.randint(0, vocab, (3, 1)) for _ in range(4)]
    with fluid.scope_guard(parameters.scope):
        cv, mv = exe.run(
            fluid.io.get_inference_program([ctx, mh]),
            feed={"words": make_seq(seqs, dtype=np.int32)},
            fetch_list=[ctx, mh], mode="infer")
    assert np.asarray(cv).shape == (4, 10)
    assert np.asarray(mv).shape == (4, 8)


def test_v2_gru_group_matches_simple_gru():
    """gru_group over a pre-projected sequence computes the SAME values as
    the underlying fluid dynamic_gru when sharing parameters by name —
    the reference's group/simple_* equivalence, checked numerically."""
    vocab = 10
    paddle.init(seed=4)
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(vocab))
    emb = paddle.layer.embedding(
        input=words, size=6,
        param_attr=paddle.attr.Param(name="gg_emb"))
    proj = paddle.layer.fc(input=emb, size=12, bias_attr=False,
                           param_attr=paddle.attr.Param(name="gg_proj"))
    out = paddle.networks.gru_group(
        proj, size=4, param_attr=paddle.attr.Param(name="gg_rec_w"),
        bias_attr=paddle.attr.Param(name="gg_rec_b"))
    assert out.lod_level == 1 and tuple(out.shape)[-1] == 4

    from paddle_tpu import fluid
    from paddle_tpu.fluid import make_seq

    ref = fluid.layers.dynamic_gru(
        input=proj, size=4,
        param_attr=fluid.ParamAttr(name="gg_rec_w"),
        bias_attr=fluid.ParamAttr(name="gg_rec_b"))

    out2 = paddle.networks.lstmemory_group(
        paddle.layer.fc(input=emb, size=16, bias_attr=False), size=4)
    assert out2.lod_level == 1 and tuple(out2.shape)[-1] == 4

    cost = paddle.layer.mse_cost(
        input=paddle.layer.pool(out, pool_type=paddle.pooling.Sum()),
        label=paddle.layer.data(
            name="y", type=paddle.data_type.dense_vector(4)))
    parameters = paddle.parameters.create(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(2)
    seqs = [rng.randint(0, vocab, (3, 1)) for _ in range(4)]
    with fluid.scope_guard(parameters.scope):
        exe.run(fluid.default_startup_program())
        a, b = exe.run(
            fluid.io.get_inference_program([out, ref]),
            feed={"words": make_seq(seqs, dtype=np.int32)},
            fetch_list=[out, ref], mode="infer")
    np.testing.assert_allclose(np.asarray(a.data), np.asarray(b.data),
                               atol=1e-6)


def test_v2_straggler_layers_compute_and_train():
    """Round-5 straggler tail (COMPAT.md): slope_intercept / dot_prod /
    sum_to_one_norm / clip / l2_distance / interpolation compute the
    documented math, and a config using scale_shift + hsigmoid trains."""
    paddle.init(seed=31)
    from paddle_tpu import fluid

    a = paddle.layer.data(name="a", type=paddle.data_type.dense_vector(4))
    b = paddle.layer.data(name="b", type=paddle.data_type.dense_vector(4))
    w = paddle.layer.data(name="w", type=paddle.data_type.dense_vector(1))
    si = paddle.layer.slope_intercept(a, slope=2.0, intercept=1.0)
    dp = paddle.layer.dot_prod(a, b)
    s1 = paddle.layer.sum_to_one_norm(a)
    cl = paddle.layer.clip(a, min=0.25, max=0.5)
    l2 = paddle.layer.l2_distance(a, b)
    ip = paddle.layer.interpolation([a, b], w)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    av = np.array([[0.1, 0.2, 0.3, 0.4]], np.float32)
    bv = np.array([[0.4, 0.3, 0.2, 0.1]], np.float32)
    wv = np.array([[0.25]], np.float32)
    with fluid.scope_guard(scope):
        exe.run(fluid.default_startup_program())
        o = exe.run(fluid.default_main_program(),
                    feed={"a": av, "b": bv, "w": wv},
                    fetch_list=[si, dp, s1, cl, l2, ip])
    si_v, dp_v, s1_v, cl_v, l2_v, ip_v = (np.asarray(x) for x in o)
    np.testing.assert_allclose(si_v, av * 2 + 1, rtol=1e-6)
    np.testing.assert_allclose(dp_v, (av * bv).sum(-1, keepdims=True),
                               rtol=1e-6)
    np.testing.assert_allclose(s1_v, av / av.sum(), rtol=1e-6)
    np.testing.assert_allclose(cl_v, np.clip(av, 0.25, 0.5), rtol=1e-6)
    np.testing.assert_allclose(
        l2_v, np.sqrt(((av - bv) ** 2).sum(-1, keepdims=True)), rtol=1e-6)
    np.testing.assert_allclose(ip_v, 0.25 * av + 0.75 * bv, rtol=1e-6)

    # hsigmoid + scale_shift config trains end-to-end via SGD.train
    paddle.init(seed=32)
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(8))
    y = paddle.layer.data(name="y", type=paddle.data_type.integer_value(6))
    h = paddle.layer.fc(input=x, size=12, act=paddle.activation.Tanh())
    h2 = paddle.layer.scale_shift(h)
    cost = paddle.layer.hsigmoid(input=h2, label=y, num_classes=6)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3))
    rng = np.random.RandomState(5)

    def reader():
        for _ in range(32):
            v = rng.rand(8).astype(np.float32)
            yield v, int(v[0] * 6) % 6

    costs = []
    trainer.train(reader=paddle.batch(reader, 8), num_passes=6,
                  event_handler=lambda ev: costs.append(ev.cost)
                  if isinstance(ev, paddle.event.EndIteration) else None,
                  feeding={"x": 0, "y": 1})
    assert np.isfinite(costs).all() and costs[-1] < costs[0]


def test_v2_prelu_and_conv_network_helpers():
    """prelu (channel mode aligned to NCHW dim 1) + img_conv_bn_pool /
    img_separable_conv / small_vgg network helpers (COMPAT.md rows)."""
    from paddle_tpu import fluid

    paddle.init(seed=13)
    main, startup = (fluid.default_main_program(),
                     fluid.default_startup_program())
    scope = fluid.Scope()
    x = fluid.layers.data("x", [3, 4, 4], "float32")
    y1 = fluid.layers.prelu(x, mode="channel",
                            param_attr=fluid.ParamAttr(name="alpha"))
    exe = fluid.Executor(fluid.CPUPlace())
    xs = np.random.RandomState(0).randn(2, 3, 4, 4).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        al = np.asarray(scope.find_var("alpha"))
        o, = exe.run(main, feed={"x": xs}, fetch_list=[y1])
    np.testing.assert_allclose(
        np.asarray(o), np.where(xs > 0, xs, al.reshape(1, 3, 1, 1) * xs),
        rtol=1e-6)

    paddle.init(seed=14)
    img = paddle.layer.data(
        name="img", type=paddle.data_type.dense_vector(3 * 16 * 16))
    r = fluid.layers.reshape(img, [-1, 3, 16, 16])
    c1 = paddle.networks.img_conv_bn_pool(
        r, filter_size=3, num_filters=4, pool_size=2, pool_stride=2,
        act=paddle.activation.Relu())
    c2 = paddle.networks.img_separable_conv(
        c1, num_channels=4, num_out_channels=8, filter_size=3, padding=1,
        act=paddle.activation.Relu())
    p1 = paddle.layer.prelu(c2)
    lab = paddle.layer.data(name="lab",
                            type=paddle.data_type.integer_value(2))
    pred = paddle.layer.fc(input=p1, size=2,
                           act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=pred, label=lab)
    params = paddle.parameters.create(cost)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2))
    rng = np.random.RandomState(4)

    def reader():
        for _ in range(8):
            v = rng.rand(3 * 16 * 16).astype(np.float32)
            yield v, int(v.mean() > 0.5)

    costs = []
    tr.train(reader=paddle.batch(reader, 4), num_passes=3,
             event_handler=lambda ev: costs.append(ev.cost)
             if isinstance(ev, paddle.event.EndIteration) else None,
             feeding={"img": 0, "lab": 1})
    assert np.isfinite(costs).all()

    paddle.init(seed=15)
    img2 = paddle.layer.data(
        name="i2", type=paddle.data_type.dense_vector(3 * 32 * 32))
    r2 = fluid.layers.reshape(img2, [-1, 3, 32, 32])
    out = paddle.networks.small_vgg(r2, num_channels=3, num_classes=10)
    assert tuple(out.shape)[-1] == 10


def test_v2_factorization_machine():
    """FM second-order term matches the O(n^2) pair sum on a toy input
    and trains inside a CTR-style head (COMPAT.md row 106)."""
    from paddle_tpu import fluid

    paddle.init(seed=21)
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(5))
    fm = paddle.layer.factorization_machine(
        x, factor_size=3, param_attr=paddle.attr.Param(name="fm_v"))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    xs = np.random.RandomState(0).rand(4, 5).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(fluid.default_startup_program())
        V = np.asarray(scope.find_var("fm_v"))
        o, = exe.run(fluid.default_main_program(), feed={"x": xs},
                     fetch_list=[fm])
    want = np.zeros((4, 1), np.float32)
    for b in range(4):
        for i in range(5):
            for j in range(i + 1, 5):
                want[b, 0] += V[i] @ V[j] * xs[b, i] * xs[b, j]
    np.testing.assert_allclose(np.asarray(o), want, rtol=1e-4, atol=1e-6)

    # trains: FM + linear term as a CTR head
    paddle.init(seed=22)
    x2 = paddle.layer.data(name="x2",
                           type=paddle.data_type.dense_vector(8))
    y2 = paddle.layer.data(name="y2",
                           type=paddle.data_type.integer_value(2))
    fm2 = paddle.layer.factorization_machine(x2, factor_size=4)
    lin = paddle.layer.fc(input=x2, size=1)
    both = paddle.layer.concat([fm2, lin])
    pred = paddle.layer.fc(input=both, size=2,
                           act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=pred, label=y2)
    params = paddle.parameters.create(cost)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3))
    rng = np.random.RandomState(6)

    def reader():
        for _ in range(24):
            v = rng.rand(8).astype(np.float32)
            yield v, int((v[0] * v[1]) > 0.25)   # an interaction label

    costs = []
    tr.train(reader=paddle.batch(reader, 8), num_passes=6,
             event_handler=lambda ev: costs.append(ev.cost)
             if isinstance(ev, paddle.event.EndIteration) else None,
             feeding={"x2": 0, "y2": 1})
    assert np.isfinite(costs).all() and costs[-1] < costs[0]


def test_v2_cost_and_shape_wrappers():
    """huber costs / repeat / power / out_prod / gated_unit
    (COMPAT.md rows 27, 31, 59, 85, 86, 94) compute the documented
    math."""
    from paddle_tpu import fluid

    paddle.init(seed=41)
    a = paddle.layer.data(name="a", type=paddle.data_type.dense_vector(3))
    b = paddle.layer.data(name="b", type=paddle.data_type.dense_vector(3))
    w = paddle.layer.data(name="w", type=paddle.data_type.dense_vector(1))
    yl = paddle.layer.data(name="yl", type=paddle.data_type.integer_value(2))
    pr = paddle.layer.data(name="pr", type=paddle.data_type.dense_vector(1))
    rep_r = paddle.layer.repeat(a, 2, as_row_vector=True)
    rep_e = paddle.layer.repeat(a, 2, as_row_vector=False)
    pw = paddle.layer.power(a, w)
    op = paddle.layer.out_prod(a, b)
    hr = paddle.layer.huber_regression_cost(pr, w, delta=1.0)
    hc = paddle.layer.huber_classification_cost(pr, yl)
    gu = paddle.layer.gated_unit(a, size=4)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    av = np.array([[1., 2., 3.]], np.float32)
    bv = np.array([[4., 5., 6.]], np.float32)
    wv = np.array([[2.0]], np.float32)
    prv = np.array([[0.5]], np.float32)
    ylv = np.array([[1]], np.int64)
    with fluid.scope_guard(scope):
        exe.run(fluid.default_startup_program())
        o = exe.run(fluid.default_main_program(),
                    feed={"a": av, "b": bv, "w": wv, "pr": prv,
                          "yl": ylv},
                    fetch_list=[rep_r, rep_e, pw, op, hr, hc, gu])
    rr, re, pwv, opv, hrv, hcv, guv = (np.asarray(x) for x in o)
    np.testing.assert_allclose(rr, [[1, 2, 3, 1, 2, 3]], rtol=1e-6)
    np.testing.assert_allclose(re, [[1, 1, 2, 2, 3, 3]], rtol=1e-6)
    np.testing.assert_allclose(pwv, av ** 2.0, rtol=1e-6)
    np.testing.assert_allclose(opv, np.outer(av, bv).reshape(1, 9),
                               rtol=1e-6)
    # huber classification: y=+1, f=0.5 -> yf=0.5 >= -1 -> (1-0.5)^2
    np.testing.assert_allclose(hcv, [0.25], rtol=1e-5)
    # huber regression delta=1: r = w - pr = 1.5 > delta -> 1*(1.5-0.5)
    np.testing.assert_allclose(hrv, [1.0], rtol=1e-5)
    assert guv.shape == (1, 4)
    # delta=2 branch shapes: |r|=1.5 <= 2 -> 0.5*1.5^2 = 1.125
    hr2 = paddle.layer.huber_regression_cost(pr, w, delta=2.0)
    with fluid.scope_guard(scope):
        o3, = exe.run(fluid.default_main_program(),
                      feed={"a": av, "b": bv, "w": wv, "pr": prv,
                            "yl": ylv},
                      fetch_list=[hr2])
    np.testing.assert_allclose(np.asarray(o3), [1.125], rtol=1e-5)
    # the -4yf branch: y=0 (mapped -1), f=3 -> yf=-3 < -1 -> 12
    with fluid.scope_guard(scope):
        o2, = exe.run(fluid.default_main_program(),
                      feed={"a": av, "b": bv, "w": wv,
                            "pr": np.array([[3.0]], np.float32),
                            "yl": np.array([[0]], np.int64)},
                      fetch_list=[hc])
    np.testing.assert_allclose(np.asarray(o2), [12.0], rtol=1e-5)
