"""v2 API layer: reference-shaped scripts (paddle.init / layer DSL /
trainer.SGD(train(reader=..., event_handler=...)) / parameters tar /
infer) running on the fluid/XLA engine — VERDICT r1 #6's contract:
fit_a_line and MNIST v2-style scripts train with an import swap.
"""

import io as pyio

import numpy as np

import paddle_tpu.v2 as paddle


def _housing_reader(rng, n=64):
    w = np.arange(1, 14, dtype=np.float32) / 13.0

    def reader():
        for _ in range(n):
            x = rng.randn(13).astype(np.float32)
            y = np.array([x @ w], np.float32)
            yield x, y

    return reader


def test_v2_fit_a_line_trains_and_infers():
    paddle.init(use_gpu=False, trainer_count=1, seed=7)
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(13))
    y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(1))
    y_predict = paddle.layer.fc(input=x, size=1,
                                act=paddle.activation.Linear())
    cost = paddle.layer.mse_cost(input=y_predict, label=y)

    parameters = paddle.parameters.create(cost)
    optimizer = paddle.optimizer.Momentum(momentum=0.9, learning_rate=2e-2)
    trainer = paddle.trainer.SGD(cost=cost, parameters=parameters,
                                 update_equation=optimizer)

    events = {"costs": [], "passes": []}

    def event_handler(event):
        if isinstance(event, paddle.event.EndIteration):
            events["costs"].append(event.cost)
        elif isinstance(event, paddle.event.EndPass):
            events["passes"].append(event.pass_id)

    rng = np.random.RandomState(0)
    trainer.train(reader=paddle.batch(_housing_reader(rng), batch_size=16),
                  num_passes=6, event_handler=event_handler,
                  feeding={"x": 0, "y": 1})
    assert events["passes"] == list(range(6))
    assert events["costs"][-1] < events["costs"][0] * 0.3, \
        events["costs"][::8]

    # test() runs the inference clone
    result = trainer.test(reader=paddle.batch(_housing_reader(rng, 32), 16),
                          feeding={"x": 0, "y": 1})
    assert np.isfinite(result.cost)

    # parameters round-trip through the v2 tar format
    buf = pyio.BytesIO()
    trainer.save_parameter_to_tar(buf)
    buf.seek(0)
    w_before = parameters["fc_0.w_0"] if "fc_0.w_0" in parameters.names() \
        else parameters[parameters.names()[0]]
    parameters.set(parameters.names()[0],
                   np.zeros_like(w_before))
    parameters.from_tar(buf)
    np.testing.assert_array_equal(parameters[parameters.names()[0]],
                                  w_before)

    # infer matches a manual forward
    batch_rows = [(np.ones(13, np.float32) * 0.1,)]
    probs = paddle.infer(output_layer=y_predict, parameters=parameters,
                         input=batch_rows, feeding={"x": 0})
    assert probs.shape == (1, 1) and np.isfinite(probs).all()


def test_v2_mnist_mlp_trains():
    paddle.init(use_gpu=False, trainer_count=1, seed=11)
    images = paddle.layer.data(name="pixel",
                               type=paddle.data_type.dense_vector(64))
    label = paddle.layer.data(name="label",
                              type=paddle.data_type.integer_value(10))
    h1 = paddle.layer.fc(input=images, size=32,
                         act=paddle.activation.Relu())
    predict = paddle.layer.fc(input=h1, size=10,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=label)

    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3))

    rng = np.random.RandomState(1)

    def reader():
        # synthetic digits: class k = bright k-th row of an 8x8 image
        for _ in range(96):
            k = rng.randint(0, 10)
            img = rng.rand(64).astype(np.float32) * 0.1
            img[(k % 8) * 8: (k % 8) * 8 + 8] += 1.0
            img[k % 64] += float(k) / 10.0
            yield img, int(k)

    costs = []

    def handler(event):
        if isinstance(event, paddle.event.EndIteration):
            costs.append(event.cost)

    trainer.train(reader=paddle.batch(reader, batch_size=32),
                  num_passes=8, event_handler=handler)
    assert costs[-1] < costs[0] * 0.7, costs[::8]

    # infer returns class probabilities for raw rows
    rows = [(np.ones(64, np.float32) * 0.2,)]
    probs = paddle.infer(output_layer=predict, parameters=parameters,
                         input=rows, feeding={"pixel": 0})
    assert probs.shape == (1, 10)
    np.testing.assert_allclose(probs.sum(), 1.0, rtol=1e-4)


def test_v2_sequence_classification():
    """sequence data types flow through the v2 feeder (SeqArray)."""
    paddle.init(seed=3)
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(30))
    label = paddle.layer.data(name="label",
                              type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=words, size=8)
    pooled = paddle.layer.pool(input=emb, pool_type=paddle.pooling.Max)
    predict = paddle.layer.fc(input=pooled, size=2,
                              act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=predict, label=label)
    parameters = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2))

    rng = np.random.RandomState(5)

    def reader():
        for _ in range(64):
            pos = rng.randint(0, 2)
            lo, hi = (0, 15) if pos == 0 else (15, 30)
            yield rng.randint(lo, hi, rng.randint(2, 7)).tolist(), pos

    costs = []
    trainer.train(
        reader=paddle.batch(reader, batch_size=16), num_passes=6,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0], (costs[0], costs[-1])


def test_v2_test_does_not_train():
    """r2 review: trainer.test() must be forward-only — evaluating on a
    reader cannot move parameters."""
    paddle.init(seed=13)
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(1))
    pred = paddle.layer.fc(input=x, size=1,
                           act=paddle.activation.Linear())
    cost = paddle.layer.mse_cost(input=pred, label=y)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.1))
    rng = np.random.RandomState(2)

    def reader():
        for _ in range(8):
            xv = rng.randn(4).astype(np.float32)
            yield xv, np.array([xv.sum()], np.float32)

    trainer.train(reader=paddle.batch(reader, 4), num_passes=1)
    name = params.names()[0]
    before = params[name].copy()
    trainer.test(reader=paddle.batch(reader, 4))
    np.testing.assert_array_equal(params[name], before)


def test_v2_partial_batch_yields():
    """r2 review: v2 batch keeps the trailing partial batch (reference
    minibatch contract); 5 rows @ batch 4 -> 2 batches."""
    rows = [(np.zeros(2, np.float32),)] * 5
    batches = list(paddle.batch(lambda: iter(rows), 4)())
    assert [len(b) for b in batches] == [4, 1]


def test_v2_embedding_requires_int_data_layer():
    import pytest

    paddle.init()
    x = paddle.layer.data(name="xf", type=paddle.data_type.dense_vector(4))
    with pytest.raises(ValueError, match="integer data layer"):
        paddle.layer.embedding(input=x, size=8)
