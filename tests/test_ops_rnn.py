"""Numeric-gradient OpTest coverage for the recurrent ops (reference
test_lstm_op.py / test_gru_op.py / test_gru_unit_op.py pattern)."""

import numpy as np
import pytest

from op_test import OpTestCase
from paddle_tpu.fluid import make_seq

R = np.random.RandomState(13)


def _seq(batch_lens, feat):
    return make_seq([R.uniform(-0.5, 0.5, (n, feat)).astype(np.float32)
                     for n in batch_lens])


def _r(*shape):
    return R.uniform(-0.5, 0.5, shape).astype(np.float32)


class TestDynamicLSTM:
    def _case(self, use_peepholes, is_reverse=False):
        hid = 2
        x = _seq([3, 1], 4 * hid)
        w = _r(hid, 4 * hid)
        b = _r(7 * hid if use_peepholes else 4 * hid)
        return OpTestCase("dynamic_lstm",
                          {"Input": x, "Weight": w, "Bias": b},
                          {"use_peepholes": use_peepholes,
                           "is_reverse": is_reverse})

    @pytest.mark.parametrize("peep", [False, True])
    def test_grad(self, peep):
        t = self._case(peep)
        t.check_grad(["Input", "Weight", "Bias"], output_slots=["Hidden"],
                     max_relative_error=3e-2)

    def test_reverse_grad(self):
        t = self._case(False, is_reverse=True)
        t.check_grad(["Input", "Weight"], output_slots=["Hidden"],
                     max_relative_error=3e-2)

    def test_forward_manual(self):
        """One-step sequence against hand-computed gates (c~,i,f,o order)."""
        hid = 2
        x = make_seq([R.uniform(-0.5, 0.5, (1, 4 * hid)).astype(np.float32)])
        w = _r(hid, 4 * hid)
        b = np.zeros(4 * hid, np.float32)
        t = OpTestCase("dynamic_lstm", {"Input": x, "Weight": w, "Bias": b},
                       {"use_peepholes": False})
        g = np.asarray(x.data)[0, 0]
        gc, gi, gf, go = np.split(g, 4)
        sig = lambda v: 1 / (1 + np.exp(-v))
        c = sig(gi) * np.tanh(gc)          # h0=c0=0 → forget term drops
        h = sig(go) * np.tanh(c)
        exp_h = h[None, None, :]
        t.check_output({"Hidden": make_seq([exp_h[0]]),
                        "Cell": make_seq([c[None, :]])}, atol=1e-5)


class TestDynamicGRU:
    def test_grad(self):
        hid = 3
        x = _seq([3, 2], 3 * hid)
        w = _r(hid, 3 * hid)
        b = _r(3 * hid)
        t = OpTestCase("dynamic_gru", {"Input": x, "Weight": w, "Bias": b})
        t.check_grad(["Input", "Weight", "Bias"], max_relative_error=3e-2)

    def test_update_gate_convention(self):
        """u→1 must follow the CANDIDATE (reference gru_kernel.h:62)."""
        hid = 1
        xv = np.zeros((1, 1, 3 * hid), np.float32)
        xv[0, 0, 0] = 100.0   # update gate saturates to 1
        xv[0, 0, 2] = 5.0     # candidate ~ tanh(5) ~ 1
        x = make_seq([xv[0]])
        w = np.zeros((hid, 3 * hid), np.float32)
        t = OpTestCase("dynamic_gru", {"Input": x, "Weight": w})
        exp = np.tanh(5.0) * np.ones((1, 1, 1), np.float32)
        t.check_output({"Hidden": make_seq([exp[0]])}, atol=1e-5)


class TestUnits:
    def test_lstm_unit_grad(self):
        x, c = _r(4, 8), _r(4, 2)
        t = OpTestCase("lstm_unit", {"X": x, "C_prev": c},
                       {"forget_bias": 1.0})
        t.check_grad(["X", "C_prev"], output_slots=["H"],
                     max_relative_error=2e-2)

    def test_gru_unit_grad(self):
        hid = 3
        x, h = _r(4, 3 * hid), _r(4, hid)
        w, b = _r(hid, 3 * hid), _r(3 * hid)
        t = OpTestCase("gru_unit",
                       {"Input": x, "HiddenPrev": h, "Weight": w, "Bias": b})
        t.check_grad(["Input", "HiddenPrev", "Weight"],
                     output_slots=["Hidden"], max_relative_error=2e-2)
