"""Nested (level-2) LoD integration — VERDICT r2 missing#7 / next#7.

The reference's 2-level LoD uses (lod_tensor.h:109): beam decode's
per-source candidate lists (beam_search_decode_op.cc) and nested
sequence structure (paragraph→sentence→words).  These tests wire
NestedSeqArray through real programs: the decode output carries real
nested lengths, nested sequence_expand gets a numeric check, and a
conll05-style pipeline pools paragraph→sentence→vector→prediction.
"""

import numpy as np

from paddle_tpu import fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.core.lod import (NestedSeqArray, SeqArray,
                                       make_nested_seq, make_seq)
from paddle_tpu.models import machine_translation as mt

DICT = 12
START, END = 0, 1


def test_beam_decode_outputs_nested_lengths(fresh_programs):
    """decode_model's SentenceIds is a NestedSeqArray whose inner
    lengths stop at each hypothesis's first end_id — the per-source
    candidate-list structure of beam_search_decode_op.cc."""
    main, startup, scope = fresh_programs
    src = fluid.layers.data(name="src", shape=[1], dtype="int64",
                            lod_level=1)
    ids_out, scores_out = mt.decode_model(src, DICT, word_dim=8,
                                          hidden_dim=16, beam_size=3,
                                          topk_size=10, max_length=5)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    srcs = [rng.randint(2, DICT, rng.randint(3, 5)) for _ in range(4)]
    out, sc = exe.run(main, feed={"src": make_seq(srcs, dtype=np.int64)},
                      fetch_list=[ids_out, scores_out],
                      return_numpy=False)
    assert isinstance(out, NestedSeqArray)
    data = np.asarray(out.data)                 # [B, W, T]
    inner = np.asarray(out.inner_lengths)       # [B, W]
    outer = np.asarray(out.outer_lengths)       # [B]
    assert data.shape[:2] == (4, 3)
    np.testing.assert_array_equal(outer, [3, 3, 3, 3])
    assert (inner >= 1).all() and (inner <= data.shape[2]).all()
    # the length really marks the first END (or the full row)
    for b in range(4):
        for w in range(3):
            hyp = data[b, w]
            ln = inner[b, w]
            if END in hyp.tolist():
                assert hyp[ln - 1] == END
                assert END not in hyp[: ln - 1].tolist()
            else:
                assert ln == data.shape[2]
    # scores sorted best-first
    sc = np.asarray(sc)
    assert (np.diff(sc, axis=1) <= 1e-6).all()


def test_nested_sequence_expand_numeric(fresh_programs):
    """sequence_expand over a level-2 Y: each outer element of X
    broadcasts over its sub-sequence's inner steps, padding stays 0."""
    main, startup, scope = fresh_programs
    x = fluid.layers.data(name="x", shape=[2], dtype="float32",
                          lod_level=1)
    y = fluid.layers.data(name="y", shape=[1], dtype="float32",
                          lod_level=2)
    out = layers.sequence_expand(x, y)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    xv = make_seq([[[1., 1.], [2., 2.]], [[3., 3.]]], dtype=np.float32)
    yv = make_nested_seq([[[5., 6., 7.], [8.]], [[9., 9.]]],
                         dtype=np.float32)
    res, = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[out],
                   return_numpy=False)
    assert isinstance(res, NestedSeqArray)
    d = np.asarray(res.data)                   # [2, 2, 3, 2]
    np.testing.assert_array_equal(np.asarray(res.outer_lengths), [2, 1])
    np.testing.assert_array_equal(np.asarray(res.inner_lengths),
                                  [[3, 1], [2, 0]])
    # row 0, sub-seq 0 (3 steps): x[0,0] broadcast
    np.testing.assert_allclose(d[0, 0], [[1, 1], [1, 1], [1, 1]])
    # row 0, sub-seq 1 (1 step): x[0,1]; padding zeroed
    np.testing.assert_allclose(d[0, 1], [[2, 2], [0, 0], [0, 0]])
    # row 1, sub-seq 0 (2 steps): x[1,0]
    np.testing.assert_allclose(d[1, 0], [[3, 3], [3, 3], [0, 0]])
    np.testing.assert_allclose(d[1, 1], 0)


def test_paragraph_sentence_pooling_pipeline(fresh_programs):
    """conll05-style nested pipeline: paragraphs (outer) of sentences
    (inner) of word embeddings -> nested inner pool -> level-1 outer
    pool -> classifier; trains end-to-end through the nested grads."""
    main, startup, scope = fresh_programs
    startup.random_seed = 7  # deterministic init for the convergence assert
    vocab, dim = 20, 6
    words = fluid.layers.data(name="words", shape=[1], dtype="int64",
                              lod_level=2)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    emb = layers.embedding(input=words, size=[vocab, dim],
                           param_attr="nested_emb_w")
    sent_vecs = layers.nested_sequence_pool(emb, pool_type="average")
    para_vec = layers.sequence_pool(input=sent_vecs, pool_type="max")
    pred = fluid.layers.fc(input=para_vec, size=2, act="softmax")
    cost = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
    fluid.optimizer.Adam(learning_rate=5e-2).minimize(cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(3)

    def batch(n=8):
        paras, labels = [], []
        for _ in range(n):
            pol = rng.randint(0, 2)
            lo, hi = (2, vocab // 2) if pol == 0 else (vocab // 2, vocab)
            n_sent = rng.randint(1, 4)
            paras.append([rng.randint(lo, hi, rng.randint(1, 5)).tolist()
                          for _ in range(n_sent)])
            labels.append([pol])
        return (make_nested_seq(paras, dtype=np.int64),
                np.asarray(labels, np.int64))

    wv, lv = batch()
    losses = []
    for _ in range(30):
        l, = exe.run(main, feed={"words": wv, "label": lv},
                     fetch_list=[cost])
        losses.append(float(np.asarray(l)))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_nested_flatten_outer_roundtrip():
    nested = make_nested_seq([[[1, 2], [3]], [[4, 5, 6]]],
                             dtype=np.float32)
    flat = nested.flatten_outer()
    assert isinstance(flat, SeqArray)
    assert flat.data.shape[0] == 4          # batch 2 x max_outer 2
    np.testing.assert_array_equal(np.asarray(flat.lengths), [2, 1, 3, 0])
