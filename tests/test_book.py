"""End-to-end "book" tests — mirror of fluid/tests/book/: full training
loops asserting the loss decreases.  Synthetic data (zero-egress CI), tiny
shapes, CPU mesh; the same model builders run full-size on TPU via bench.py.
"""

import numpy as np
import pytest

from paddle_tpu import fluid
from paddle_tpu.fluid import make_seq
from paddle_tpu.models import (image_classification, recognize_digits,
                               sentiment, word2vec)


def _train(main, startup, scope, feeder, loss_var, steps=25, acc_var=None):
    """startup=None skips the init run (scope already initialized)."""
    # every book program doubles as static-analyzer acceptance coverage:
    # forward + append_backward + optimizer must re-check clean
    fetch = [loss_var] + ([acc_var] if acc_var is not None else [])
    diag = main.analyze(level="full", fetch_list=fetch)
    assert not diag.has_errors, diag.render()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        if startup is not None:
            exe.run(startup)
        losses = []
        for i in range(steps):
            fetch = [loss_var] + ([acc_var] if acc_var is not None else [])
            out = exe.run(main, feed=feeder(i), fetch_list=fetch)
            losses.append(float(out[0]))
    return losses


def test_recognize_digits_conv(fresh_programs):
    main, startup, scope = fresh_programs
    startup.random_seed = 7  # deterministic init for convergence asserts
    img = fluid.layers.data(name="img", shape=[1, 28, 28], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    _, avg_cost, acc = recognize_digits.conv_net(img, label)
    fluid.optimizer.Adam(learning_rate=0.01).minimize(avg_cost)

    rng = np.random.RandomState(0)
    # synthetic "digits": class k = bright kth row-band
    def feeder(i):
        lbl = rng.randint(0, 10, (16, 1)).astype(np.int64)
        img_v = rng.rand(16, 1, 28, 28).astype(np.float32) * 0.1
        for b, k in enumerate(lbl[:, 0]):
            img_v[b, 0, k * 2: k * 2 + 3, :] += 1.0
        return {"img": img_v, "label": lbl}

    losses = _train(main, startup, scope, feeder, avg_cost, steps=30)
    assert losses[-1] < losses[0] * 0.6, losses[::6]


def test_word2vec_ngram(fresh_programs):
    main, startup, scope = fresh_programs
    startup.random_seed = 7  # deterministic init for convergence asserts
    dict_size = 30
    words = [fluid.layers.data(name=f"w{i}", shape=[1], dtype="int64")
             for i in range(5)]
    avg_cost, _ = word2vec.ngram_model(words, dict_size, embed_size=8,
                                       hidden_size=32)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(avg_cost)

    rng = np.random.RandomState(1)

    def feeder(i):
        ctx = rng.randint(0, dict_size, (32, 4))
        nxt = (ctx.sum(axis=1) % dict_size).reshape(-1, 1)
        feed = {f"w{k}": ctx[:, k:k + 1].astype(np.int64) for k in range(4)}
        feed["w4"] = nxt.astype(np.int64)
        return feed

    losses = _train(main, startup, scope, feeder, avg_cost, steps=40)
    assert losses[-1] < losses[0]


def test_image_classification_resnet_small(fresh_programs):
    main, startup, scope = fresh_programs
    startup.random_seed = 7  # deterministic init for convergence asserts
    img = fluid.layers.data(name="img", shape=[3, 32, 32], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    # depth 8 = smallest valid CIFAR resnet ((8-2)%6==0); 32px input is
    # what the builder's final 8x8 avg pool assumes
    predict = image_classification.resnet_cifar10(img, depth=8, class_num=4)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.Momentum(learning_rate=0.02, momentum=0.9).minimize(
        avg_cost)

    rng = np.random.RandomState(2)

    def feeder(i):
        lbl = rng.randint(0, 4, (8, 1)).astype(np.int64)
        img_v = rng.rand(8, 3, 32, 32).astype(np.float32) * 0.2
        for b, k in enumerate(lbl[:, 0]):
            img_v[b, k % 3, :, :] += 0.8  # class -> dominant channel
        return {"img": img_v, "label": lbl}

    losses = _train(main, startup, scope, feeder, avg_cost, steps=30)
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses[::6]


def test_vgg_builds_and_steps(fresh_programs):
    main, startup, scope = fresh_programs
    startup.random_seed = 7  # deterministic init for convergence asserts
    img = fluid.layers.data(name="img", shape=[3, 32, 32], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    predict = image_classification.vgg16_bn_drop(img, class_num=10)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
    rng = np.random.RandomState(3)

    def feeder(i):
        return {"img": rng.rand(2, 3, 32, 32).astype(np.float32),
                "label": rng.randint(0, 10, (2, 1)).astype(np.int64)}

    losses = _train(main, startup, scope, feeder, avg_cost, steps=2)
    assert np.isfinite(losses).all()


def test_sentiment_conv_net(fresh_programs):
    main, startup, scope = fresh_programs
    startup.random_seed = 7  # deterministic init for convergence asserts
    data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                             lod_level=1)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    avg_cost, acc, _ = sentiment.convolution_net(data, label, input_dim=40,
                                                 class_dim=2, emb_dim=8,
                                                 hid_dim=8)
    fluid.optimizer.Adam(learning_rate=0.02).minimize(avg_cost)
    rng = np.random.RandomState(4)

    def feeder(i):
        seqs, lbls = [], []
        for _ in range(8):
            n = rng.randint(3, 9)
            pos = rng.randint(0, 2)
            lo, hi = (0, 20) if pos == 0 else (20, 40)
            seqs.append(rng.randint(lo, hi, (n, 1)))
            lbls.append([pos])
        return {"words": make_seq(seqs, dtype=np.int32, bucket=10),
                "label": np.array(lbls, np.int64)}

    losses = _train(main, startup, scope, feeder, avg_cost, steps=30)
    assert losses[-1] < losses[0] * 0.9


def test_sentiment_stacked_lstm(fresh_programs):
    main, startup, scope = fresh_programs
    startup.random_seed = 7  # deterministic init for convergence asserts
    data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                             lod_level=1)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    avg_cost, acc, _ = sentiment.stacked_lstm_net(
        data, label, input_dim=30, class_dim=2, emb_dim=8, hid_dim=8,
        stacked_num=3)
    fluid.optimizer.Adam(learning_rate=0.02).minimize(avg_cost)
    rng = np.random.RandomState(5)

    def feeder(i):
        seqs, lbls = [], []
        for _ in range(6):
            n = rng.randint(2, 7)
            pos = rng.randint(0, 2)
            lo, hi = (0, 15) if pos == 0 else (15, 30)
            seqs.append(rng.randint(lo, hi, (n, 1)))
            lbls.append([pos])
        return {"words": make_seq(seqs, dtype=np.int32, bucket=8),
                "label": np.array(lbls, np.int64)}

    losses = _train(main, startup, scope, feeder, avg_cost, steps=20)
    assert losses[-1] < losses[0]


def test_recommender_system(fresh_programs):
    """book ch.05 (test_recommender_system.py): dual-tower MovieLens net
    learns a synthetic rating signal."""
    from paddle_tpu.models import recommender as R

    main, startup, scope = fresh_programs
    startup.random_seed = 7  # deterministic init for convergence asserts
    dims = R.MovieLensDims(max_user_id=40, max_job_id=10, n_age_buckets=7,
                           max_movie_id=60, n_categories=10,
                           title_dict_size=80)
    avg_cost, scale_infer = R.recommender(dims)
    fluid.optimizer.SGD(learning_rate=0.2).minimize(avg_cost)

    rng = np.random.RandomState(3)
    batch = 16

    def feeder(i):
        uid = rng.randint(0, dims.max_user_id, (batch, 1))
        mid = rng.randint(0, dims.max_movie_id, (batch, 1))
        cats = [rng.randint(0, dims.n_categories,
                            rng.randint(1, 4)).tolist() for _ in range(batch)]
        titles = [rng.randint(0, dims.title_dict_size,
                              rng.randint(3, 8)).tolist()
                  for _ in range(batch)]
        # learnable signal: rating depends on user/movie parity
        score = (2.5 + ((uid + mid) % 2) * 2.0).astype(np.float32)
        return {
            "user_id": uid.astype(np.int64),
            "gender_id": (uid % 2).astype(np.int64),
            "age_id": (uid % dims.n_age_buckets).astype(np.int64),
            "job_id": (uid % dims.max_job_id).astype(np.int64),
            "movie_id": mid.astype(np.int64),
            "category_id": make_seq(cats, dtype=np.int32, bucket=4),
            "movie_title": make_seq(titles, dtype=np.int32, bucket=8),
            "score": score,
        }

    losses = _train(main, startup, scope, feeder, avg_cost, steps=30)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.6, losses[::10]


def test_label_semantic_roles(fresh_programs):
    """book ch.07 (test_label_semantic_roles.py): db_lstm + CRF loss
    decreases; Viterbi decode improves against the gold tags."""
    from paddle_tpu.models import label_semantic_roles as L

    main, startup, scope = fresh_programs
    startup.random_seed = 7  # deterministic init for convergence asserts
    dims = L.SRLDims(word_dict_len=30, label_dict_len=5, pred_len=8,
                     hidden_dim=16, depth=2)
    avg_cost, feature_out, crf_decode, target, _ = L.srl_model(dims)
    fluid.optimizer.SGD(learning_rate=0.05).minimize(avg_cost)

    rng = np.random.RandomState(0)
    batch, bucket = 8, 6

    def feeder(i):
        lens = rng.randint(2, bucket + 1, batch)
        words = [rng.randint(0, dims.word_dict_len, l).tolist()
                 for l in lens]
        # gold labels derivable from the word ids (mod label count)
        tags = [[w % dims.label_dict_len for w in ws] for ws in words]
        feed = {"word_data": make_seq(words, dtype=np.int32, bucket=bucket),
                "target": make_seq(tags, dtype=np.int32, bucket=bucket)}
        for n in ("ctx_n2_data", "ctx_n1_data", "ctx_0_data",
                  "ctx_p1_data", "ctx_p2_data"):
            feed[n] = make_seq(words, dtype=np.int32, bucket=bucket)
        feed["verb_data"] = make_seq(
            [[w % dims.pred_len for w in ws] for ws in words],
            dtype=np.int32, bucket=bucket)
        feed["mark_data"] = make_seq(
            [[w % 2 for w in ws] for ws in words], dtype=np.int32,
            bucket=bucket)
        return feed

    def decode_accuracy():
        """Viterbi path vs gold tags on a fixed probe batch."""
        exe = fluid.Executor(fluid.CPUPlace())
        probe_rng = np.random.RandomState(42)
        lens = probe_rng.randint(2, bucket + 1, batch)
        words = [probe_rng.randint(0, dims.word_dict_len, l).tolist()
                 for l in lens]
        tags = [[w % dims.label_dict_len for w in ws] for ws in words]
        feed = {"word_data": make_seq(words, dtype=np.int32, bucket=bucket),
                "target": make_seq(tags, dtype=np.int32, bucket=bucket),
                "verb_data": make_seq(
                    [[w % dims.pred_len for w in ws] for ws in words],
                    dtype=np.int32, bucket=bucket),
                "mark_data": make_seq(
                    [[w % 2 for w in ws] for ws in words],
                    dtype=np.int32, bucket=bucket)}
        for n in ("ctx_n2_data", "ctx_n1_data", "ctx_0_data",
                  "ctx_p1_data", "ctx_p2_data"):
            feed[n] = make_seq(words, dtype=np.int32, bucket=bucket)
        with fluid.scope_guard(scope):
            path, = exe.run(main, feed=feed, fetch_list=[crf_decode])
        path = np.asarray(path.data if hasattr(path, "data") else path)
        path = path.reshape(path.shape[0], path.shape[1], -1)[:, :, 0]
        correct = total = 0
        for b, ws in enumerate(words):
            for t, w in enumerate(ws):
                correct += int(path[b, t] == w % dims.label_dict_len)
                total += 1
        return correct / total

    exe0 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe0.run(startup)
    acc_before = decode_accuracy()
    losses = _train(main, None, scope, feeder, avg_cost, steps=30)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses[::10]
    # the decoded Viterbi path must improve against gold — proves
    # crf_decoding shares the trained 'crfw' transitions
    acc_after = decode_accuracy()
    assert acc_after > acc_before + 0.1, (acc_before, acc_after)


def test_bf16_activation_training(fresh_programs):
    """Mixed precision: bf16 activations + f32 master weights (the TPU
    recipe; r2 conv PET fix) — a conv net trains without dtype errors
    and the loss decreases."""
    main, startup, scope = fresh_programs
    startup.random_seed = 7  # deterministic init for convergence asserts
    img = fluid.layers.data(name="img", shape=[3, 16, 16],
                            dtype="bfloat16")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    conv = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                               padding=1, act="relu")
    pool = fluid.layers.pool2d(input=conv, pool_size=2, pool_stride=2)
    predict = fluid.layers.fc(input=pool, size=4, act="softmax")
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9).minimize(
        avg_cost)
    import ml_dtypes
    rng = np.random.RandomState(0)

    def feeder(i):
        lbl = rng.randint(0, 4, (8, 1)).astype(np.int64)
        imgv = (rng.rand(8, 3, 16, 16) * 0.2).astype(ml_dtypes.bfloat16)
        for b, k in enumerate(lbl[:, 0]):
            imgv[b, k % 3] += ml_dtypes.bfloat16(0.8)
        return {"img": imgv, "label": lbl}

    losses = _train(main, startup, scope, feeder, avg_cost, steps=25)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, losses[::5]


def test_benchmark_nets_build_and_smallnet_trains(fresh_programs):
    """The reference's GPU-benchmark image configs (benchmark/paddle/image
    alexnet/googlenet/smallnet — the K40m rows in BASELINE.md) build with
    the right output shapes; the cheap one trains a step end-to-end.
    (AlexNet/GoogLeNet train on TPU in bench.py's image_suite; full CPU
    training steps of 224px nets are too slow for unit CI.)"""
    from paddle_tpu.models import benchmark_nets as B

    for fn, px, ncls in [(B.alexnet, 227, 1000),
                         (B.googlenet_v1, 224, 1000)]:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup), fluid.unique_name.guard():
            img = fluid.layers.data("img", [3, px, px], "float32")
            pred = fn(img, class_num=ncls)
        assert tuple(pred.shape)[-1] == ncls

    main, startup, scope = fresh_programs
    startup.random_seed = 7  # deterministic init for convergence asserts
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        img = fluid.layers.data("img", [3, 32, 32], "float32")
        label = fluid.layers.data("label", [1], "int64")
        pred = B.smallnet_cifar(img)
        cost = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        fluid.optimizer.Momentum(learning_rate=0.01,
                                 momentum=0.9).minimize(cost)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    # one FIXED batch: with a fresh random batch per step the decrease is
    # marginal (random labels) and can flip under thread-count-dependent
    # float rounding — memorizing a single batch decreases robustly
    feed = {"img": rng.rand(16, 3, 32, 32).astype(np.float32),
            "label": rng.randint(0, 10, (16, 1)).astype(np.int64)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = [float(np.asarray(exe.run(main, feed=feed,
                                           fetch_list=[cost])[0]))
                  for _ in range(8)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
