"""SPMD parallelism tests on the 8-device virtual CPU mesh — the same-process
multi-device testing SURVEY.md §4 calls for (the reference couldn't test its
distributed path in CI at all; its dist tests were `notest_`)."""

import jax
import numpy as np
import pytest

from paddle_tpu import fluid
from paddle_tpu import parallel


def _build_fit_a_line():
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(input=x, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return loss


def _train(loss, main, startup, scope, steps=20, seed=0):
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(seed)
    true_w = rng.randn(16, 1).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        out = []
        for _ in range(steps):
            xv = rng.randn(32, 16).astype(np.float32)
            yv = xv @ true_w
            lv, = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
            out.append(float(lv))
    return out


def test_data_parallel_matches_single_device(fresh_programs):
    """The SAME program trains identically under dp=8 sharding (modulo fp
    reduction order) — the capability parallel_do/MultiGradientMachine
    provided, now via pure annotation."""
    main, startup, scope = fresh_programs
    main.random_seed = 1234
    startup.random_seed = 99  # identical init in both runs
    loss = _build_fit_a_line()

    single = _train(loss, main, startup, scope, steps=15)

    scope2 = fluid.Scope()
    mesh = parallel.make_mesh({"dp": 8})
    with parallel.mesh_guard(mesh):
        dp = _train(loss, main, startup, scope2, steps=15)

    np.testing.assert_allclose(single, dp, rtol=2e-4, atol=1e-5)
    assert dp[-1] < dp[0] * 0.5


def test_data_parallel_shards_feed_compute(fresh_programs):
    """Check the compiled step really places sharded feeds across devices."""
    main, startup, scope = fresh_programs
    loss = _build_fit_a_line()
    mesh = parallel.make_mesh({"dp": 8})
    with parallel.mesh_guard(mesh), fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xv = np.random.randn(32, 16).astype(np.float32)
        yv = np.random.randn(32, 1).astype(np.float32)
        exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
        w = [p for p in main.global_block().all_parameters()
             if tuple(p.shape) == (16, 1)][0]
        wv = scope.find_var(w.name)
        # replicated param: every device holds it
        assert len(wv.sharding.device_set) == 8


def test_tensor_parallel_sharded_param(fresh_programs):
    """fc weight sharded over 'mp' (ParallelNeuralNetwork analog)."""
    main, startup, scope = fresh_programs
    x = fluid.layers.data(name="x", shape=[8], dtype="float32")
    h = fluid.layers.fc(
        input=x, size=32,
        param_attr=fluid.ParamAttr(sharding=(None, "mp")), bias_attr=False)
    out = fluid.layers.reduce_sum(h)
    mesh = parallel.make_mesh({"dp": 2, "mp": 4})
    exe = fluid.Executor(fluid.CPUPlace())
    with parallel.mesh_guard(mesh), fluid.scope_guard(scope):
        exe.run(startup)
        xv = np.random.randn(4, 8).astype(np.float32)
        ov, = exe.run(main, feed={"x": xv}, fetch_list=[out])
        w = main.global_block().all_parameters()[0]
        wv = scope.find_var(w.name)
        spec = wv.sharding.spec
        assert tuple(spec) == (None, "mp"), spec
        wv_np = np.asarray(wv)
        np.testing.assert_allclose(ov, (xv @ wv_np).sum(), rtol=1e-4)


def test_dp_with_tp_training_step(fresh_programs):
    """Full train step with both axes: dp-sharded batch, mp-sharded fc."""
    main, startup, scope = fresh_programs
    x = fluid.layers.data(name="x", shape=[8], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h = fluid.layers.fc(input=x, size=16, act="relu",
                        param_attr=fluid.ParamAttr(sharding=(None, "mp")))
    p = fluid.layers.fc(input=h, size=1,
                        param_attr=fluid.ParamAttr(sharding=("mp", None)))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(p, y))
    fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    mesh = parallel.make_mesh({"dp": 4, "mp": 2})
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(5)
    with parallel.mesh_guard(mesh), fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(20):
            xv = rng.randn(16, 8).astype(np.float32)
            yv = xv.sum(1, keepdims=True)
            lv, = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
            losses.append(float(lv))
    assert losses[-1] < losses[0]


def test_optimizer_accumulators_shard_with_param(fresh_programs):
    """Adam moments of an mp-sharded weight inherit the param's sharding
    annotation instead of replicating on every device (VERDICT weak #6)."""
    main, startup, scope = fresh_programs
    x = fluid.layers.data(name="x", shape=[8], dtype="float32")
    h = fluid.layers.fc(input=x, size=16,
                        param_attr=fluid.ParamAttr(sharding=(None, "mp")),
                        bias_attr=False)
    loss = fluid.layers.mean(h)
    opt = fluid.optimizer.Adam(learning_rate=0.01)
    opt.minimize(loss)
    w = main.global_block().all_parameters()[0]
    m1 = opt._get_accumulator("moment1", w)
    assert m1.desc.sharding == [None, "mp"]
    mesh = parallel.make_mesh({"dp": 4, "mp": 2})
    exe = fluid.Executor(fluid.CPUPlace())
    with parallel.mesh_guard(mesh), fluid.scope_guard(scope):
        exe.run(startup)
        xv = np.random.randn(8, 8).astype(np.float32)
        exe.run(main, feed={"x": xv}, fetch_list=[loss])
        mv = scope.find_var(m1.name)
        assert not mv.sharding.is_fully_replicated
        wv = scope.find_var(w.name)
        assert not wv.sharding.is_fully_replicated


def test_zero_style_moment_sharding(fresh_programs):
    """Opt-in ZeRO: moments of a *replicated* param shard over 'dp', and
    training still converges."""
    main, startup, scope = fresh_programs
    startup.random_seed = 7  # deterministic init for the convergence assert
    x = fluid.layers.data(name="x", shape=[8], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    p = fluid.layers.fc(input=x, size=1, bias_attr=False)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(p, y))
    opt = fluid.optimizer.Adam(learning_rate=0.05, shard_moments_over="dp")
    opt.minimize(loss)
    w = main.global_block().all_parameters()[0]
    m1 = opt._get_accumulator("moment1", w)
    assert m1.desc.sharding == ["dp?", None]
    mesh = parallel.make_mesh({"dp": 8})
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(3)
    losses = []
    with parallel.mesh_guard(mesh), fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(15):
            xv = rng.randn(16, 8).astype(np.float32)
            yv = xv.sum(1, keepdims=True)
            lv, = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
            losses.append(float(lv))
        mv = scope.find_var(m1.name)
        assert not mv.sharding.is_fully_replicated
        # the param's desc annotation stays replicated — XLA may leave the
        # updated value dp-sharded after the step (ZeRO semantics); the
        # executor re-gathers it against its annotation on the next run
        assert w.sharding is None
    assert losses[-1] < losses[0] * 0.5


def test_transpiler_annotates_params(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    h = fluid.layers.fc(input=x, size=2048, bias_attr=False)
    loss = fluid.layers.mean(h)
    opt = fluid.optimizer.Adam(learning_rate=0.1)
    opt_ops, pg = opt.minimize(loss)
    t = parallel.DistributeTranspiler()
    t.transpile(opt_ops, pg, trainers=4, mesh_axes={"dp": 4, "mp": 2})
    w = [p for p in main.global_block().all_parameters()
         if 2048 in p.shape][0]
    assert w.sharding is not None and "mp" in w.sharding
    assert t.mesh_axes["dp"] == 4
    # accumulators created by minimize (before transpile) pick up the
    # param's annotation too — moments must not replicate
    m1 = opt._get_accumulator("moment1", w)
    assert m1.desc.sharding == list(w.sharding)
    # scalar beta-pow accumulators stay unannotated
    b1 = opt._get_accumulator("beta1_pow_acc", w)
    assert b1.desc.sharding is None
    # reference-API surface intact
    assert t.get_pserver_program("h:0").global_block() is not None


def test_seq_model_data_parallel(fresh_programs):
    """SeqArray feeds shard over dp too (data + lengths)."""
    from paddle_tpu.fluid import make_seq

    main, startup, scope = fresh_programs
    words = fluid.layers.data(name="w", shape=[1], dtype="int64",
                              lod_level=1)
    emb = fluid.layers.embedding(input=words, size=[30, 8])
    pooled = fluid.layers.sequence_pool(emb, "average")
    logits = fluid.layers.fc(input=pooled, size=3)
    loss = fluid.layers.mean(logits)
    mesh = parallel.make_mesh({"dp": 8})
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(9)
    with parallel.mesh_guard(mesh), fluid.scope_guard(scope):
        exe.run(startup)
        seqs = [rng.randint(0, 30, (rng.randint(1, 6), 1))
                for _ in range(16)]
        lv, = exe.run(main, feed={"w": make_seq(seqs, np.int32, bucket=8)},
                      fetch_list=[loss])
    assert np.isfinite(lv)


def test_zero_markers_merge_with_transpile(fresh_programs):
    """Adam(shard_moments_over='dp') + transpile(mp) must leave moments
    sharded over BOTH axes — the deferred 'dp?' marker merges with the
    param's mp annotation instead of blocking it (r2 review finding)."""
    main, startup, scope = fresh_programs
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    h = fluid.layers.fc(input=x, size=2048, bias_attr=False)
    loss = fluid.layers.mean(h)
    opt = fluid.optimizer.Adam(learning_rate=0.1, shard_moments_over="dp")
    opt_ops, pg = opt.minimize(loss)
    t = parallel.DistributeTranspiler()
    t.transpile(opt_ops, pg, trainers=4, mesh_axes={"dp": 4, "mp": 2})
    w = [p for p in main.global_block().all_parameters()
         if 2048 in p.shape][0]
    assert "mp" in w.sharding
    m1 = opt._get_accumulator("moment1", w)
    assert "mp" in m1.desc.sharding          # param's axis propagated
    assert "dp?" in m1.desc.sharding         # ZeRO marker survived the merge


def test_feed_sharding_never_materializes_array_likes(fresh_programs):
    """feed_sharding must read only .shape on the feed leaf: np.asarray on a
    process-spanning global jax.Array raises 'non-addressable shards', and
    it is exactly the documented multi-host fast path (r3 advice, medium)."""
    mesh = parallel.make_mesh({"dp": 8})

    class GlobalArrayStub:
        shape = (32, 16)

        def __array__(self, dtype=None):
            raise RuntimeError("np.asarray on a non-addressable global array")

    sh = parallel.feed_sharding(mesh, GlobalArrayStub())
    assert sh.spec == jax.sharding.PartitionSpec("dp")


def test_pre_sharded_device_feed(fresh_programs):
    """Feeding already-device-resident jax.Arrays (the multi-host fast path:
    each process device_puts its local shard) trains identically to host
    numpy feeds."""
    main, startup, scope = fresh_programs
    main.random_seed = 77
    startup.random_seed = 55
    loss = _build_fit_a_line()

    scope_np = fluid.Scope()
    host = _train(loss, main, startup, scope_np, steps=10, seed=3)

    mesh = parallel.make_mesh({"dp": 8})
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(3)
    true_w = rng.randn(16, 1).astype(np.float32)
    sharded = []
    with parallel.mesh_guard(mesh), fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        from jax.sharding import NamedSharding, PartitionSpec
        sh = NamedSharding(mesh, PartitionSpec("dp"))
        for _ in range(10):
            xv = rng.randn(32, 16).astype(np.float32)
            yv = xv @ true_w
            xd = jax.device_put(xv, sh)
            yd = jax.device_put(yv, sh)
            lv, = exe.run(main, feed={"x": xd, "y": yd}, fetch_list=[loss])
            sharded.append(float(lv))
    np.testing.assert_allclose(host, sharded, rtol=2e-4, atol=1e-5)
