"""Elastic multi-host chaos end-to-end (marked slow; the fast
deterministic halves live in test_coordinator.py).

The flagship scenario: three subprocess "hosts" rendezvous through one
PodCoordinator, train a shared fluid regression in lockstep (gradients
mean-reduced through the per-step agreement barrier), and a seeded
FaultInjector SIGKILLs one host at a precomputed step_sync entry.  The
survivors must detect the loss, re-rendezvous at world 2, rewind to the
last committed pod manifest, and finish with zero lost or duplicated
steps and bitwise-identical parameters — with an injected single-host
NaN earlier in the run becoming an agreed pod-wide skip, and a
pre-seeded torn (uncommitted) manifest never restored.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from paddle_tpu.fluid.checkpoint import PodCheckpointManager
from paddle_tpu.parallel import CoordinatorServer
from paddle_tpu.resilience import FaultInjector

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow

# seed 5 @ p=0.12: FaultInjector.decision(5, "coord.crash", i) first
# fires at draw index 4.  PodClient.step_sync draws once per call, so
# the victim SIGKILLs itself entering the barrier for step 5 — after
# the world-3 manifests at steps 2 and 4 committed.
CRASH_SEED, CRASH_PROB, CRASH_STEP = 5, 0.12, 5
NAN_STEP = 2          # a SURVIVOR poisons this step -> agreed pod skip
MAX_STEPS = 8

POD_WORKER = """
    import os, sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    addr, ckpt_dir, out_dir, host = sys.argv[1:5]
    max_steps = int(os.environ["POD_MAX_STEPS"])
    nan_step = int(os.environ.get("POD_NAN_STEP", "0"))
    nan_host = os.environ.get("POD_NAN_HOST", "")

    from paddle_tpu import fluid
    from paddle_tpu.parallel import PodClient
    from paddle_tpu.resilience import ResilientTrainer

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11   # identical init pod-wide
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data("x", [4], "float32")
        y = fluid.layers.data("y", [1], "float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        pairs = fluid.append_backward(loss)       # fetch grads, apply later
    exe = fluid.Executor(fluid.CPUPlace())
    params = [p.name for p, _ in pairs]
    gvars = [g for _, g in pairs]

    W = np.array([1.5, -2.0, 0.5, 3.0], np.float32)

    def read_chunk(step, rank, world):
        r = np.random.RandomState(step)           # one global batch per step
        xs = r.randn(12, 4).astype(np.float32)
        ys = (xs @ W[:, None]).astype(np.float32)
        return xs[rank::world], ys[rank::world]   # this host's shard

    losses = open(os.path.join(out_dir, host + ".losses"), "a")

    def train_step(rec, step):
        xs, ys = rec
        out = exe.run(main, feed={"x": xs, "y": ys},
                      fetch_list=[loss] + gvars)
        losses.write(f"{step} {float(np.asarray(out[0]))}\\n")
        losses.flush()
        grads = {n: np.asarray(g) for n, g in zip(params, out[1:])}
        if step == nan_step and host == nan_host:
            grads = {k: v * np.nan for k, v in grads.items()}
        return True, grads

    def apply_update(reduced, step):
        for name in params:
            cur = np.asarray(scope.find_var(name))
            scope.set_var(name,
                          (cur - 0.05 * reduced[name]).astype(np.float32))

    client = PodClient(addr, host, poll_interval=0.05)
    trainer = ResilientTrainer(
        ckpt_dir, coordinator=client, read_chunk=read_chunk,
        apply_update=apply_update, program=main, scope=scope,
        save_interval_steps=2, rendezvous_deadline=60.0,
        step_deadline=60.0, heartbeat_interval=0.2)

    def cold_init():
        # marker written HERE (not at exit): the chaos victim never
        # reaches exit, but its cold start must still be observable
        open(os.path.join(out_dir, host + ".fresh"), "w").close()
        exe.run(startup)

    with fluid.scope_guard(scope):
        final = trainer.run(train_step, init_fn=cold_init,
                            max_steps=max_steps)
    state = {n: np.asarray(scope.find_var(n)) for n in params}
    np.savez(os.path.join(out_dir, host + ".final.npz"), **state)
    print("WORKER-DONE", final, flush=True)
"""


def _clean_env(extra=None):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO + os.pathsep
                + os.environ.get("PYTHONPATH", "")})
    env.update(extra or {})
    return env


def _effective_timeline(events):
    """Replay one host's pod-* journal entries: verdicts advance the
    timeline, resync/rollback-restore rewind it (discarding every
    later entry — those steps were never durably applied).  Returns the
    surviving [(step, verdict)] in order."""
    line = []
    for rec in events:
        if rec["event"] in ("pod-resync", "pod-rollback-restore"):
            line = [(s, v) for s, v in line if s <= rec["step"]]
        else:
            line.append((rec["step"], rec["event"]))
    return line


def test_chaos_host_loss_re_rendezvous_and_lockstep_recovery(tmp_path):
    script = str(tmp_path / "pod_worker.py")
    open(script, "w").write(textwrap.dedent(POD_WORKER))
    ckpt = str(tmp_path / "pod")
    out = str(tmp_path / "out")
    os.makedirs(out)
    journal = str(tmp_path / "chaos.journal")

    # a torn manifest from "before": one staged rank, no COMMIT marker.
    # Recovery must never restore it — every host cold-starts instead.
    torn = PodCheckpointManager(ckpt)
    torn.stage(999, 0, 3, {"fc_0.w_0": np.full((4, 1), 77.0, np.float32)})
    assert torn.latest_committed() is None

    srv = CoordinatorServer(world_min=1, world_target=3,
                            heartbeat_timeout=2.0, vote_timeout=4.0)
    addr = srv.start()
    procs = {}
    try:
        base = {"POD_MAX_STEPS": str(MAX_STEPS),
                "POD_NAN_STEP": str(NAN_STEP),
                "POD_NAN_HOST": "host-a"}
        victim_extra = {"PADDLE_TPU_CHAOS":
                        f"coord.crash={CRASH_PROB}",
                        "PADDLE_TPU_CHAOS_SEED": str(CRASH_SEED),
                        "PADDLE_TPU_CHAOS_LOG": journal}
        for host in ("host-a", "host-b", "host-c"):
            extra = dict(base)
            if host == "host-c":
                extra.update(victim_extra)
            procs[host] = subprocess.Popen(
                [sys.executable, script, addr, ckpt, out, host],
                env=_clean_env(extra), cwd=str(tmp_path))

        # the victim dies by its own seeded hand at step 5's barrier
        assert procs["host-c"].wait(timeout=120) == -9

        # survivors detect the loss and re-rendezvous at world 2
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = srv.status()
            if st["world"] == 2 and "host-c" not in st["members"]:
                break
            time.sleep(0.1)
        assert st["world"] == 2 and st["host_losses"] == 1, st

        for host in ("host-a", "host-b"):
            assert procs[host].wait(timeout=180) == 0, host
        final_status = srv.status()
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        srv.stop()

    # every host cold-started: the torn pod-999 manifest was skipped
    for host in ("host-a", "host-b", "host-c"):
        assert os.path.exists(os.path.join(out, host + ".fresh")), host

    # the pod's durable result is the final step, restorable, and the
    # torn manifest is still not committed
    pm = PodCheckpointManager(ckpt)
    assert pm.latest_committed() == MAX_STEPS
    assert 999 not in pm.committed_steps()
    assert final_status["last_committed"] == MAX_STEPS
    step, items = pm.restore(0)
    assert step == MAX_STEPS

    # bitwise-identical parameters across the survivors, matching the
    # committed manifest
    fa = np.load(os.path.join(out, "host-a.final.npz"))
    fb = np.load(os.path.join(out, "host-b.final.npz"))
    assert set(fa.files) == set(fb.files) and fa.files
    for name in fa.files:
        assert fa[name].tobytes() == fb[name].tobytes(), name
        assert items[name].tobytes() == fa[name].tobytes(), name

    # training converged through the NaN-skip and the host loss
    for host in ("host-a", "host-b"):
        lines = [ln.split() for ln in
                 open(os.path.join(out, host + ".losses"))]
        vals = [float(v) for _, v in lines]
        assert vals[-1] < vals[0], (host, vals[0], vals[-1])

    # journal audit: identical agreed verdicts wherever two hosts saw
    # the same (generation, step); zero lost or duplicated steps after
    # rewinds; the only effective skip is the agreed NaN step
    per_host = {}
    verdicts = {}
    for ln in open(os.path.join(ckpt, "guard.journal")):
        rec = json.loads(ln)
        if not rec["event"].startswith("pod-"):
            continue
        per_host.setdefault(rec["host"], []).append(rec)
        if rec["event"] not in ("pod-resync", "pod-rollback-restore"):
            key = (rec["generation"], rec["step"])
            verdicts.setdefault(key, set()).add(rec["event"])
    for key, events in verdicts.items():
        assert len(events) == 1, (key, events)
    for host in ("host-a", "host-b"):
        line = _effective_timeline(per_host[host])
        assert [s for s, _ in line] == list(range(1, MAX_STEPS + 1)), \
            (host, line)
        assert {s for s, v in line if v == "pod-skip"} == {NAN_STEP}, \
            (host, line)
        # the loss really forced a rewind: a resync below the crash step
        assert any(r["event"] == "pod-resync"
                   and r["step"] < CRASH_STEP
                   for r in per_host[host]), host

    # determinism: every journaled chaos draw replays from the seed,
    # and the fatal draw is the precomputed one
    fired = []
    for ln in open(journal):
        if ln.startswith("#") or not ln.strip():
            continue
        point, index, value, hit = ln.split()
        assert point == "coord.crash"
        want = FaultInjector.decision(CRASH_SEED, point, int(index))
        assert abs(float(value) - want) < 1e-9
        if hit == "1":
            fired.append(int(index))
    assert fired == [CRASH_STEP - 1]      # draw i belongs to step i+1
