"""BLEU metric + the opt-in real-data quality tier (VERDICT r2
missing#4 / next#5).

The metric tests always run (incl. parity against nltk's reference
implementation).  The quality tier trains on REAL downloaded data and
asserts BASELINE.md's bars — opt-in via PADDLE_TPU_REAL_DATA=1 because
it needs egress + minutes of compute; offline it skips WITH REASON, it
never silently passes.
"""

import os

import numpy as np
import pytest

from paddle_tpu.utils.bleu import corpus_bleu, sentence_bleu

REAL = os.environ.get("PADDLE_TPU_REAL_DATA") == "1"
real_data = pytest.mark.skipif(
    not REAL, reason="real-data quality tier is opt-in: set "
    "PADDLE_TPU_REAL_DATA=1 with network egress (downloads MNIST/WMT)")


class TestBleuMetric:
    def test_perfect_match_is_one(self):
        hyp = "the cat sat on the mat".split()
        assert corpus_bleu([hyp], [[hyp]]) == pytest.approx(1.0)

    def test_no_overlap_is_zero(self):
        assert corpus_bleu([list("abcd")], [[list("wxyz")]]) == 0.0

    def test_clipping(self):
        # "the the the" vs "the cat": p1 clipped to 1/3, p2 = 0 -> BLEU 0
        assert corpus_bleu([["the", "the", "the"]],
                           [[["the", "cat"]]]) == 0.0

    def test_brevity_penalty(self):
        hyp = "the cat".split()
        ref = "the cat sat on the mat".split()
        got = corpus_bleu([hyp], [[ref]], max_n=2)
        # p1 = 1, p2 = 1, bp = exp(1 - 6/2)
        assert got == pytest.approx(np.exp(1 - 6 / 2), rel=1e-6)

    def test_matches_nltk_reference_implementation(self):
        from nltk.translate.bleu_score import corpus_bleu as nltk_bleu

        rng = np.random.RandomState(0)
        hyps, refs = [], []
        vocab = [f"w{i}" for i in range(30)]
        for _ in range(20):
            n = rng.randint(5, 15)
            ref = [vocab[i] for i in rng.randint(0, 30, n)]
            hyp = list(ref)
            for _ in range(rng.randint(0, 4)):    # corrupt a few tokens
                hyp[rng.randint(0, len(hyp))] = vocab[rng.randint(0, 30)]
            hyps.append(hyp)
            refs.append([ref])
        ours = corpus_bleu(hyps, refs)
        theirs = nltk_bleu(refs, hyps)
        assert ours == pytest.approx(theirs, rel=1e-9)

    def test_multi_reference_clipping_and_length(self):
        from nltk.translate.bleu_score import corpus_bleu as nltk_bleu

        hyp = "the fast brown fox".split()
        r1 = "the quick brown fox jumps".split()
        r2 = "a fast brown fox leapt over".split()
        ours = corpus_bleu([hyp], [[r1, r2]])
        theirs = nltk_bleu([[r1, r2]], [hyp])
        assert ours == pytest.approx(theirs, rel=1e-9)

    def test_sentence_bleu_smoothed_nonzero(self):
        got = sentence_bleu("the small cat".split(),
                            ["the tiny cat".split()])
        assert 0.0 < got < 1.0

    def test_ids_as_tokens(self):
        assert corpus_bleu([[1, 2, 3, 4]], [[[1, 2, 3, 4]]]) == 1.0


# ---------------------------------------------------------------------------
# real-data quality tier (opt-in)
# ---------------------------------------------------------------------------

@real_data
def test_mnist_top1_accuracy_real():
    """BASELINE.md: 'SGD top-1 parity' — ≥97% test top-1 on real MNIST
    with the recognize-digits conv net."""
    import jax

    from paddle_tpu import fluid
    from paddle_tpu.datasets import mnist

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        img = fluid.layers.data("img", [1, 28, 28], "float32")
        label = fluid.layers.data("label", [1], "int64")
        c1 = fluid.nets.simple_img_conv_pool(img, 20, 5, 2, 2, act="relu")
        c2 = fluid.nets.simple_img_conv_pool(c1, 50, 5, 2, 2, act="relu")
        pred = fluid.layers.fc(input=c2, size=10, act="softmax")
        cost = fluid.layers.mean(fluid.layers.cross_entropy(pred, label))
        test_prog = main.clone(for_test=True)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(cost)

    exe = fluid.Executor(fluid.TPUPlace(0))
    train_rows = list(mnist.train()())
    test_rows = list(mnist.test()())
    assert len(train_rows) >= 50000, "expected REAL mnist (60k rows)"

    def batches(rows, bs):
        for i in range(0, len(rows) - bs + 1, bs):
            chunk = rows[i: i + bs]
            x = np.stack([r[0].reshape(1, 28, 28) for r in chunk])
            y = np.asarray([[r[1]] for r in chunk], np.int64)
            yield x, y

    with fluid.scope_guard(scope):
        exe.run(startup)
        for epoch in range(3):
            for x, y in batches(train_rows, 128):
                exe.run(main, feed={"img": x, "label": y},
                        fetch_list=[cost])
        correct = total = 0
        for x, y in batches(test_rows, 500):
            p, = exe.run(test_prog, feed={"img": x, "label": y},
                         fetch_list=[pred], mode="infer")
            correct += (np.asarray(p).argmax(1) == y[:, 0]).sum()
            total += len(y)
    top1 = correct / total
    print(f"MNIST top-1: {top1:.4f} ({correct}/{total})")
    assert top1 >= 0.97, top1


@real_data
def test_nmt_bleu_real():
    """Train the seq2seq model on real WMT16 pairs and record corpus
    BLEU of greedy decodes (the BASELINE.md 'achieved' number)."""
    from paddle_tpu import fluid
    from paddle_tpu.datasets import wmt16
    from paddle_tpu.fluid.core.lod import make_seq
    from paddle_tpu.models import machine_translation as mt

    dict_size = 2000
    rows = []
    for i, r in enumerate(wmt16.train(dict_size)()):
        rows.append(r)
        if i >= 4999:
            break
    assert len(rows) >= 1000, "expected real wmt16 data"

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        src = fluid.layers.data("src", [1], "int64", lod_level=1)
        trg = fluid.layers.data("trg", [1], "int64", lod_level=1)
        nxt = fluid.layers.data("nxt", [1], "int64", lod_level=1)
        avg_cost, _ = mt.train_model(src, trg, nxt, dict_size,
                                     word_dim=64, hidden_dim=128)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
        ids_out, _ = mt.decode_model(src, dict_size, word_dim=64,
                                     hidden_dim=128, beam_size=3,
                                     max_length=16)
    exe = fluid.Executor(fluid.TPUPlace(0))

    def batch(rs):
        # wmt16 rows are (src_ids, trg_ids_next, trg_ids_in)
        return (make_seq([r[0] for r in rs], dtype=np.int64),
                make_seq([r[1] for r in rs], dtype=np.int64),
                make_seq([r[2] for r in rs], dtype=np.int64))

    with fluid.scope_guard(scope):
        exe.run(startup)
        for epoch in range(2):
            for i in range(0, len(rows) - 32, 32):
                s, n, t = batch(rows[i: i + 32])
                exe.run(main, feed={"src": s, "trg": t, "nxt": n},
                        fetch_list=[avg_cost])
        hyps, refs = [], []
        infer_prog = fluid.io.prune_program(main, [ids_out])
        for i in range(0, 512, 32):
            s, n, _ = batch(rows[i: i + 32])
            out, = exe.run(infer_prog, feed={"src": s},
                           fetch_list=[ids_out],
                           return_numpy=False, mode="infer")
            best = np.asarray(out)[:, 0]        # top beam [B, T]
            for b in range(best.shape[0]):
                hyp = [int(w) for w in best[b] if w > 1]   # strip pads
                ref = [int(w) for w in np.asarray(n.data)[b]
                       if w > 1]
                hyps.append(hyp)
                refs.append([ref])
    bleu = corpus_bleu(hyps, refs, smooth=True)
    print(f"NMT corpus BLEU (train-subset decode): {bleu:.4f}")
    assert bleu > 0.0
