"""Concurrency sanitizer tests (ISSUE 13): the ordered-lock runtime
checker (rank inversions and lock-order cycles detected at acquire time
with both acquisition sites), the ``syncheck`` static lint (raw locks,
blocking I/O under locks, predicate-free condition waits), the
``paddle_sync_*`` accounting + blocked-thread statusz dump, and the
seeded-schedule race harness: scheduler + gateway + journals + release
controller driven through deterministic ``sync.preempt`` perturbation
schedules asserting zero lost/duplicated requests, clean journal
replay, exact metric counts, and ``PageAllocator.check_invariants``.

Regression notes for the syncheck satellite sweep over paddle_tpu/
(every real finding the lint surfaced, each fixed in this PR):

* ``resilience/chaos.py`` ``FaultInjector._log`` wrote (open + write)
  the chaos journal INSIDE its draw lock — every injection point in
  every thread serialized behind the disk.  Fixed: the lock now covers
  only the draw index; appends are lock-free single-line O_APPEND
  writes (``test_chaos_log_concurrent_lines_intact``).
* ``native/__init__.py`` ``_load`` ran the g++ subprocess + dlopen
  under the publish lock — the first analyzer call held every other
  one (even already-answered lookups) behind a multi-second compile.
  Fixed: the build serializes under a dedicated ``native.build`` lock
  (two concurrent ``make`` runs writing the .so in place could publish
  a corrupt artifact); the publish lock is held only for the
  flag/pointer swap.
* ``lifecycle/controller.py`` verdict polling audit: the probe waits
  and ``run()``'s ``time.sleep`` hold NO lock (confirmed clean), but
  ``status()`` — called from ObservabilityServer HTTP threads —
  iterated ``state.bad``/``state.directives`` while ``step()`` mutated
  them.  Fixed: ``lifecycle.controller`` lock around state commits +
  a locked snapshot in ``status()``
  (``test_controller_status_concurrent_with_step``).
* ``observability/tracing.py`` export audit: ``events()`` snapshots
  under the tracer lock and ``export()`` serializes OUTSIDE it —
  already clean; the lint run documents it stays that way.
* ``fluid/pipeline_io.py`` ``DataLoader.__iter__`` one-shot check was
  check-then-act: two concurrent iterators could both pass and
  silently split the epoch.  Fixed with the ``pipeline.loader`` lock
  (``test_dataloader_one_shot_single_owner``).

Two production bugs found BY the seeded harness itself (both fixed in
this PR, both previously unreachable by the deterministic suites):

* ``serving/scheduler.py``: a request whose ``admit_slot`` dispatch
  was in flight — outside the scheduler lock — when ``remove_model``
  tore its lane group down was silently orphaned (activated into a
  group the step loop no longer iterates; never stepped, never
  failed).  Deterministic regression:
  ``test_admission_racing_remove_model_requeues_zero_lost``.
* ``serving/gateway/gateway.py`` ``submit``: resolve→instance TOCTOU
  against a concurrent hot swap — the alias flipped and the old
  version unloaded between the two calls, so a client submitting
  against a model that IS being served got a spurious unknown-model
  error mid-swap.  Fixed with a single re-resolve; the seeded
  gateway sweeps (submit threads racing ``swap_model``) cover it.
"""

import json
import os
import re
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability.server import resolve_source
from paddle_tpu.resilience.chaos import FaultInjector, install
from paddle_tpu.serving import PagedTransformerGenerator, copy_weights
from paddle_tpu.serving.gateway import Gateway
from paddle_tpu.serving.gateway.journal import RequestJournal
from paddle_tpu.serving.scheduler import RequestCancelled
from paddle_tpu.lifecycle import ReleaseConfig, ReleaseController
from paddle_tpu.lifecycle.journal import ReleaseJournal
from paddle_tpu.tools import syncheck
from paddle_tpu.utils import sync
from paddle_tpu.utils.sync import (DeadlockCycleError, LockOrderError,
                                   OrderedCondition, OrderedLock,
                                   OrderedRLock)

_SITE = re.compile(r"test_concurrency\.py:\d+")


@pytest.fixture
def checking():
    """Fresh registry + checking ON for the test, OFF after — so the
    rest of the suite keeps the zero-overhead passthrough."""
    sync.registry().reset()
    sync.enable_checking()
    yield sync.registry()
    sync.disable_checking()
    sync.registry().reset()


@pytest.fixture(autouse=True)
def _inert_injector():
    prev = install(FaultInjector())
    yield
    install(prev)
    sync.disable_preemption()


class EchoModel:
    """Deterministic slot model: every lane repeats its prompt's first
    token — cross-lane contamination is immediately visible."""

    start_id, end_id = 0, 1
    src_len = 64

    def __init__(self):
        self.n = 0
        self.slot_val = {}

    def open_slots(self, n):
        self.n = n

    def admit_slot(self, slot, prompt, **_):
        self.slot_val[slot] = int(np.asarray(prompt).reshape(-1)[0])
        return len(np.asarray(prompt).reshape(-1))

    def clear_slot(self, slot):
        self.slot_val.pop(slot, None)

    def step_slots(self, tokens, pos, src_len):
        return np.array([self.slot_val.get(i, 7777)
                         for i in range(self.n)], np.int64)


# -- runtime checker: detection -----------------------------------------------

def test_rank_inversion_detected_with_both_sites(checking):
    lo = OrderedLock("t13.lo", 10)
    hi = OrderedLock("t13.hi", 20)
    with hi:                                   # site A
        with pytest.raises(LockOrderError) as ei:
            lo.acquire()                       # site B: rank 10 < 20
    msg = str(ei.value)
    assert "t13.lo" in msg and "t13.hi" in msg
    assert "rank inversion" in msg
    # BOTH acquisition sites (where hi was taken, where lo is being
    # taken) are reported as file:line
    assert len(_SITE.findall(msg)) >= 2, msg
    # the held lock is still usable; ascending order stays legal
    with lo:
        with hi:
            pass


def test_two_lock_cycle_detected_with_both_sites(checking):
    a = OrderedLock("t13.a", 30)
    b = OrderedLock("t13.b", 30)               # equal rank: legal nest
    with a:
        with b:                                # records edge a -> b
            pass
    with b:
        with pytest.raises(DeadlockCycleError) as ei:
            a.acquire()                        # b -> a closes the cycle
    msg = str(ei.value)
    assert "t13.b" in msg and "t13.a" in msg and "cycle" in msg
    # both acquisition sites: this thread's (holding b, acquiring a)
    # AND the first-recorded reverse edge's sites
    assert len(_SITE.findall(msg)) >= 2, msg
    assert checking.violations >= 1


def test_same_name_nesting_is_a_cycle(checking):
    s1 = OrderedLock("t13.same", 33)
    s2 = OrderedLock("t13.same", 33)
    with s1:
        with pytest.raises(DeadlockCycleError):
            s2.acquire()


def test_self_deadlock_on_nonreentrant_lock(checking):
    lk = OrderedLock("t13.self", 35)
    with lk:
        with pytest.raises(LockOrderError, match="self-deadlock"):
            lk.acquire()


def test_rlock_reentry_and_equal_rank_ok(checking):
    r = OrderedRLock("t13.re", 40)
    other = OrderedLock("t13.other", 40)
    with r:
        assert r.locked(), "owner must see its own RLock as held"
        with r:                                # re-entry: no edge
            with other:                        # equal rank, no cycle
                pass
    assert not r.locked()
    assert checking.violations == 0


def test_condition_wait_bookkeeping_and_wait_for(checking):
    cv = OrderedCondition(name="t13.cv", rank=50)
    box = []

    def producer():
        time.sleep(0.02)
        with cv:
            box.append(1)
            cv.notify_all()

    t = threading.Thread(target=producer)
    t.start()
    with cv:
        assert cv.wait_for(lambda: box, timeout=5)
    t.join(5)
    st = checking.status()
    assert st["locks"]["t13.cv"]["acquires"] >= 2
    # nothing left held or blocked after the dance
    assert not st["blocked"]


def test_blocked_thread_stack_dump(checking):
    lk = OrderedLock("t13.blocked", 45)
    lk.acquire()
    started = threading.Event()

    def contender():
        started.set()
        with lk:
            pass

    t = threading.Thread(target=contender, name="t13-contender")
    t.start()
    started.wait(5)
    try:
        deadline = time.time() + 5
        blocked = []
        while time.time() < deadline:
            blocked = checking.status()["blocked"]
            if blocked:
                break
            time.sleep(0.005)
        assert blocked, "contender never showed in the blocked dump"
        entry = blocked[0]
        assert entry["blocked_on"].startswith("t13.blocked")
        assert "contender" in "".join(entry.get("stack", [])), \
            "stack dump must show the blocked frame"
    finally:
        lk.release()
        t.join(5)
    # statusz duck-typing: SyncRegistry attaches via its status() method
    assert resolve_source(sync.registry())()["checking"] is True


def test_sync_metrics_series_exported(checking):
    lk = OrderedLock("t13.metrics", 47)
    for _ in range(5):
        with lk:
            pass
    text = obs_metrics.registry().render_prometheus()
    assert 'paddle_sync_acquires_total{lock="t13.metrics"} 5' in text
    assert "paddle_sync_hold_seconds_total" in text
    assert "paddle_sync_contended_total" in text
    assert "paddle_sync_order_violations_total" in text


def test_toggle_checking_midstream_drops_stale_held_entries():
    """REGRESSION (review): disabling checking while a lock is held —
    its release then goes through the passthrough — must not leave a
    stale held entry that makes a later re-enable raise a spurious
    self-deadlock on the next acquire."""
    sync.registry().reset()
    sync.enable_checking()
    lk = OrderedLock("t13.toggle", 37)
    lk.acquire()
    sync.disable_checking()          # drops held bookkeeping
    lk.release()                     # passthrough release
    sync.enable_checking()
    try:
        with lk:                     # must not raise LockOrderError
            pass
    finally:
        sync.disable_checking()
        sync.registry().reset()


def test_passthrough_records_nothing_when_disabled():
    sync.registry().reset()
    lk = OrderedLock("t13.off", 49)
    with lk:
        pass
    assert sync.registry().status()["locks"] == {}


def test_real_stack_clean_under_checking(checking, tmp_path):
    """Drive the real scheduler + gateway + journal with checking ON:
    the repo rank table must hold (no inversions, no cycles), and the
    observed lock-order graph must contain the canonical nestings."""
    gw = Gateway(n_slots=2, max_new_tokens=4,
                 journal_path=str(tmp_path / "rj.jsonl"))
    gw.load_model("m", "1", instance=EchoModel())
    gw.serve()
    try:
        reqs = [gw.submit("m", [50 + i]) for i in range(6)]
        for r in reqs:
            assert r.wait(30)
    finally:
        gw.shutdown(drain=True)
    assert gw.journal.pending() == []
    assert checking.violations == 0
    g = checking.graph()
    edges = {(e["from"], e["to"]) for e in g["edges"]}
    # the canonical nestings the migration preserves
    assert ("serving.scheduler", "metrics.child") in edges
    assert ("serving.scheduler", "gateway.registry") in edges
    assert ("serving.scheduler", "gateway.journal.cv") in edges
    out = tmp_path / "graph.json"
    checking.export_graph(str(out))
    assert json.loads(out.read_text())["edges"]


# -- the static lint ----------------------------------------------------------

_FIXTURE = textwrap.dedent("""\
    import os
    import threading
    import time

    RAW = threading.Lock()

    class Bad:
        def __init__(self):
            self._lock = threading.Lock()

        def write_under_lock(self, f):
            with self._lock:
                time.sleep(0.1)
                os.fsync(f.fileno())

        def bare_wait(self, flag):
            with self._cv:
                if not flag:
                    self._cv.wait()
    """)


def test_syncheck_fixture_findings(tmp_path):
    p = tmp_path / "fixture.py"
    p.write_text(_FIXTURE)
    findings = syncheck.check_file(str(p))
    codes = sorted(f.code for f in findings)
    assert codes.count("raw-lock") == 2
    assert codes.count("io-under-lock") == 2      # sleep + fsync
    assert codes.count("wait-no-loop") == 1
    assert syncheck.main([str(p), "--quiet"]) == 1


def test_syncheck_cli_exit_codes(tmp_path):
    """Acceptance: exit 1 on the raw-lock + fsync-under-lock fixture,
    exit 0 over the real paddle_tpu tree (after the satellite fixes)."""
    p = tmp_path / "fixture.py"
    p.write_text(_FIXTURE)
    bad = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.tools.syncheck", str(p)],
        capture_output=True, text=True)
    assert bad.returncode == 1
    assert "raw-lock" in bad.stdout and "io-under-lock" in bad.stdout
    import paddle_tpu

    pkg = os.path.dirname(os.path.abspath(paddle_tpu.__file__))
    assert syncheck.main([pkg, "--quiet"]) == 0, \
        "the real tree must be syncheck-clean"


def test_syncheck_suppression_and_nested_def(tmp_path):
    src = textwrap.dedent("""\
        import os, time

        class Ok:
            def sanctioned(self, f):
                with self._lock:  # syncheck: ok
                    os.fsync(f.fileno())

            def nested(self):
                with self._lock:
                    def helper():
                        time.sleep(1)   # not run under the lock
                    return helper

            def looped_wait(self, pred):
                with self._cv:
                    while not pred():
                        self._cv.wait()
        """)
    p = tmp_path / "clean.py"
    p.write_text(src)
    assert syncheck.check_file(str(p)) == []


def test_syncheck_json_output(tmp_path):
    p = tmp_path / "fixture.py"
    p.write_text(_FIXTURE)
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.tools.syncheck", str(p),
         "--json"],
        capture_output=True, text=True)
    findings = json.loads(out.stdout)
    assert out.returncode == 1
    assert {f["code"] for f in findings} == {
        "raw-lock", "io-under-lock", "wait-no-loop"}


# -- sync.preempt determinism -------------------------------------------------

def test_preempt_schedule_is_seeded():
    a = FaultInjector(spec="sync.preempt=0.4", seed=11)
    b = FaultInjector(spec="sync.preempt=0.4", seed=11)
    c = FaultInjector(spec="sync.preempt=0.4", seed=12)
    fa = [a.maybe_preempt(max_sleep=0.0) for _ in range(64)]
    fb = [b.maybe_preempt(max_sleep=0.0) for _ in range(64)]
    fc = [c.maybe_preempt(max_sleep=0.0) for _ in range(64)]
    assert fa == fb, "same seed => same perturbation schedule"
    assert fa != fc, "different seed => different schedule"
    assert any(fa) and not all(fa)


def test_preempt_off_point_consumes_nothing():
    inj = FaultInjector(spec="master.http=0.5", seed=3)
    assert not inj.maybe_preempt()
    # the should() draw sequence is unperturbed by preempt probes
    assert [inj.should("master.http") for _ in range(4)] == \
        [FaultInjector.decision(3, "master.http", i) < 0.5
         for i in range(4)]


# -- satellite regression: chaos log off the draw lock ------------------------

def test_chaos_log_concurrent_lines_intact(tmp_path):
    log = tmp_path / "chaos.journal"
    inj = FaultInjector(spec="master.http=0.5", seed=9,
                        log_path=str(log))

    def hammer():
        for _ in range(50):
            inj.should("master.http")

    ts = [threading.Thread(target=hammer) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(10)
    lines = log.read_text().splitlines()
    assert len(lines) == 200
    pat = re.compile(r"^master\.http \d+ 0\.\d{9} [01]$")
    assert all(pat.match(ln) for ln in lines), \
        "concurrent appends interleaved mid-line"


# -- satellite regression: DataLoader one-shot race ---------------------------

def test_dataloader_one_shot_single_owner():
    from paddle_tpu.fluid.pipeline_io import DataLoader

    n = 40
    loader = DataLoader(iter([{"x": np.zeros(1)} for _ in range(n)]),
                        device_prefetch=False)
    barrier = threading.Barrier(2)
    results = [None, None]

    def consume(i):
        barrier.wait()
        try:
            results[i] = len(list(loader))
        except RuntimeError:
            results[i] = "exhausted"

    ts = [threading.Thread(target=consume, args=(i,)) for i in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(10)
    # exactly ONE thread owns the epoch; the other fails loudly —
    # never a silent split
    assert sorted(results, key=str) == [n, "exhausted"]


# -- journal ordering under seeded interleaving (satellite) -------------------

def _journal_indices(path):
    sub, done = {}, {}
    with open(path) as f:
        for i, line in enumerate(f):
            e = json.loads(line)
            (sub if e["op"] == "submit" else done)[e["jid"]] = i
    return sub, done


@pytest.mark.parametrize("seed", [1, 7])
def test_request_journal_done_never_precedes_submit(tmp_path, seed):
    """The async background writer must never reorder a ``done`` ahead
    of its ``submit`` in the file — asserted under seeded preemption at
    every lock boundary (ISSUE 13 satellite)."""
    inj = FaultInjector(spec="sync.preempt=0.3", seed=seed)
    sync.enable_preemption(inj)
    j = RequestJournal(str(tmp_path / "rq.jsonl"))

    def writer(base):
        for k in range(20):
            jid = j.new_jid()
            j.record_submit(jid, f"t{base}", "m", [base + k], 4)
            j.record_done(jid, ok=True)

    ts = [threading.Thread(target=writer, args=(100 * i,))
          for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(20)
    assert j.flush(10)
    sub, done = _journal_indices(j.path)
    assert set(sub) == set(done) and len(sub) == 60
    for jid, si in sub.items():
        assert si < done[jid], \
            f"done for {jid} reordered ahead of its submit"
    assert j.pending() == []


def test_release_journal_concurrent_appends_parse(tmp_path):
    inj = FaultInjector(spec="sync.preempt=0.3", seed=4)
    sync.enable_preemption(inj)
    j = ReleaseJournal(str(tmp_path / "rel.jsonl"), fsync=False)

    def writer(tag):
        for k in range(25):
            j.append("candidate", version=f"{tag}-{k}")

    ts = [threading.Thread(target=writer, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(20)
    entries = j.replay()
    assert len(entries) == 75, "an append was lost or merged"
    assert [e["_seq"] for e in entries] == sorted(
        e["_seq"] for e in entries)
    assert {e["version"] for e in entries} == {
        f"{i}-{k}" for i in range(3) for k in range(25)}


# -- the seeded-schedule race harness -----------------------------------------

def _event_delta(before, name="paddle_serving_requests_total"):
    after = _event_counts(name)
    return {k: after.get(k, 0.0) - before.get(k, 0.0)
            for k in set(after) | set(before)}


def _event_counts(name="paddle_serving_requests_total"):
    fam = obs_metrics.registry().get(name)
    out = {}
    if fam is None:
        return out
    for vals, child in fam.children():
        labels = dict(zip(fam.label_names, vals))
        ev = labels.get("event", "?")
        out[ev] = out.get(ev, 0.0) + child.value
    return out


def _drive_gateway_schedule(seed, tmp_path, model_factory=EchoModel,
                            n_per_tenant=6, n_slots=3, max_new=5,
                            check_invariants=False, cancel_some=True,
                            swap=True):
    """One seeded schedule: 3 client threads × n_per_tenant requests
    through a live gateway, a hot swap mid-traffic, a couple of
    cancellations — all with ``sync.preempt`` perturbing every lock
    boundary.  Asserts the ISSUE 13 contract: zero lost/duplicated
    requests, clean journal replay, exact metric counts."""
    inj = FaultInjector(spec="sync.preempt=0.25", seed=seed)
    prev = install(inj)
    sync.enable_preemption(inj)
    before = _event_counts()
    try:
        gw = Gateway(n_slots=n_slots, max_new_tokens=max_new,
                     journal_path=str(tmp_path / f"rq-{seed}.jsonl"),
                     check_invariants=check_invariants)
        gw.load_model("m", "1", instance=model_factory())
        gw.serve()
        reqs, rlock = [], threading.Lock()

        def client(tenant, base):
            for k in range(n_per_tenant):
                r = gw.submit("m", [base + k], tenant=tenant)
                with rlock:
                    reqs.append(r)
                if cancel_some and k == 2 and tenant == "t1":
                    r.cancel()

        ts = [threading.Thread(target=client,
                               args=(f"t{i}", 100 * (i + 1)))
              for i in range(3)]
        for t in ts:
            t.start()
        if swap:
            gw.swap_model("m", "2", instance=model_factory())
        for t in ts:
            t.join(60)
        for r in reqs:
            if not r.wait(60):
                import faulthandler

                st = gw.sched.stats()
                faulthandler.dump_traceback()
                raise AssertionError(
                    f"request rid={r.rid} model={r.model} "
                    f"group={r.group} slot={r.slot} "
                    f"cancelled={r.cancelled} never finished; "
                    f"sched={{steps: {st['steps']}, queued: "
                    f"{st['queued']}, in_flight: {st['in_flight']}}} "
                    f"models={st.get('models')} queued_rids="
                    f"{[q.rid for q in gw.sched.queued_requests()]} "
                    f"active={[(q.rid, q.group) for q in gw.sched.active_requests()]}")
        leftovers = gw.shutdown(drain=True)
        assert leftovers == []
        n = len(reqs)
        assert n == 3 * n_per_tenant
        cancelled = 0
        for r in reqs:
            if r.error is None:
                # no lost tokens, no duplicates, no cross-lane bleed
                assert r.tokens == [int(r.src[0])] * max_new, \
                    f"rid {r.rid}: {r.tokens} != echo of {r.src[0]}"
            else:
                assert isinstance(r.error, RequestCancelled), r.error
                cancelled += 1
        # clean journal replay: every submit has its done record
        assert gw.journal.pending() == []
        # exact metric counts for this window
        d = _event_delta(before)
        assert d.get("submitted", 0) == n
        assert d.get("finished", 0) == n - cancelled
        assert d.get("cancelled", 0) == cancelled
        assert d.get("failed", 0) == 0
        return gw
    finally:
        install(prev)
        sync.disable_preemption()


# fast subset: 3 seeded schedules (the full sweep is the slow marker)
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_race_harness_gateway_fast(seed, tmp_path):
    _drive_gateway_schedule(seed, tmp_path)


def test_admission_racing_remove_model_requeues_zero_lost():
    """REGRESSION (found by the seeded race harness, this PR): a
    request whose ``admit_slot`` dispatch was in flight — outside the
    scheduler lock — when ``remove_model`` tore its lane group down
    was silently orphaned: activated into a group the step loop no
    longer iterates, never stepped, never failed.  The fix re-queues
    it at the head; across a hot swap it re-resolves to the new
    version — zero lost."""
    from paddle_tpu.serving import ContinuousBatchingScheduler

    entered, gate = threading.Event(), threading.Event()

    class BlockingAdmitEcho(EchoModel):
        def admit_slot(self, slot, prompt, **_):
            entered.set()
            gate.wait(10)          # hold the admission mid-flight
            return super().admit_slot(slot, prompt, **_)

    alias = {"m": "m@1"}
    sched = ContinuousBatchingScheduler(
        max_new_tokens=3, resolve=lambda a: alias.get(a, a))
    sched.add_model("m@1", BlockingAdmitEcho(), 2)
    sched.serve()
    try:
        r = sched.submit([42], model="m")
        assert entered.wait(10), "admission never started"
        # hot swap while the admission dispatch is mid-flight: the new
        # version registers, the alias flips, the old group drains
        # (it sees NO active lanes — the racing admission is not
        # visible yet) and is deleted
        sched.add_model("m@2", EchoModel(), 2)
        alias["m"] = "m@2"
        sched.remove_model("m@1", drain=True, timeout=5)
        gate.set()                 # the orphaned admission completes
        assert r.wait(10), "request lost across the racing swap"
        assert r.error is None
        assert r.group == "m@2", "must re-resolve to the new version"
        assert r.tokens == [42] * 3
    finally:
        gate.set()
        sched.shutdown(drain=True)


V, SRC, OUT, PS, CHUNK = 24, 8, 6, 4, 4
GEN_KW = dict(n_layer=2, n_head=2, d_key=4, d_value=4, d_model=16,
              d_inner_hid=32, max_length=64, src_len=SRC,
              max_out_len=OUT, page_size=PS, chunk_size=CHUNK,
              num_pages=64)


@pytest.fixture(scope="module")
def paged_pair():
    from paddle_tpu import fluid

    # same param_prefix, separate scopes: copy_weights maps by NAME
    a = PagedTransformerGenerator(V, V, param_prefix="ccg",
                                  place=fluid.CPUPlace(), **GEN_KW)
    a.init_params(seed=3)
    b = PagedTransformerGenerator(V, V, param_prefix="ccg",
                                  place=fluid.CPUPlace(), **GEN_KW)
    copy_weights(a.scope, b.scope, prefix="ccg")
    return a, b


def test_race_harness_paged_invariants(paged_pair, tmp_path):
    """One seeded schedule over the REAL paged generator with
    ``check_invariants=True`` (PageAllocator audited after every
    retirement) + an explicit post-drain invariant check: no page is
    leaked or double-freed under perturbation."""
    gen, _ = paged_pair
    inj = FaultInjector(spec="sync.preempt=0.2", seed=5)
    prev = install(inj)
    sync.enable_preemption(inj)
    try:
        gw = Gateway(n_slots=2, max_new_tokens=OUT,
                     journal_path=str(tmp_path / "pq.jsonl"),
                     check_invariants=True)
        gw.load_model("m", "1", instance=gen)
        gw.serve()
        rng = np.random.RandomState(0)
        reqs = []
        for i in range(8):
            prompt = rng.randint(2, V, rng.randint(3, SRC + 1))
            reqs.append(gw.submit("m", prompt))
            if i in (2, 5):
                reqs[-1].cancel()
        for r in reqs:
            assert r.wait(120)
        gw.shutdown(drain=True)
        gen.alloc.check_invariants()
        st = gen.alloc.stats()
        assert st["in_use"] == 0, f"leaked pages after drain: {st}"
        assert gw.journal.pending() == []
        gw.unload_model("m")
    finally:
        install(prev)
        sync.disable_preemption()


def test_race_harness_controller_canary(tmp_path):
    """The release controller's canary verdict under seeded preemption
    while a second thread hammers status() (the lifecycle.controller
    lock regression test): the candidate promotes from live series,
    zero lost requests, and the poller sees no exceptions."""
    inj = FaultInjector(spec="sync.preempt=0.2", seed=8)
    prev = install(inj)
    sync.enable_preemption(inj)
    try:
        gw = Gateway(n_slots=2, max_new_tokens=4)
        cfg = ReleaseConfig("m", n_slots=2, canary_fraction=0.5,
                            canary_requests=4, p95_floor_s=5.0, seed=3)
        rc = ReleaseController(
            gw, cfg, journal_path=str(tmp_path / "rc.jsonl"),
            eval_fn=lambda inst: 1.0)
        rc.offer("1", EchoModel())
        assert rc.step() == "promoted"
        rc.offer("2", EchoModel())
        assert rc.step() == "canary-started"
        poll_err, stop = [], threading.Event()

        def poller():
            while not stop.is_set():
                try:
                    rc.status()
                except Exception as e:   # noqa: BLE001 - the assert
                    poll_err.append(e)
                    return

        t = threading.Thread(target=poller)
        t.start()
        try:
            verdict, reqs = None, []
            for i in range(24):
                batch = [gw.submit("m", [20 + 4 * i + k], max_new=4)
                         for k in range(4)]
                reqs.extend(batch)
                gw.run_until_idle()
                verdict = rc.step()
                if verdict != "canary":
                    break
            assert verdict == "promoted"
        finally:
            stop.set()
            t.join(10)
        assert not poll_err, f"status() raced step(): {poll_err[0]}"
        assert gw.registry.resolve("m") == "m@2"
        assert all(r.error is None for r in reqs), "lost requests"
    finally:
        install(prev)
        sync.disable_preemption()


# full sweep: N seeded schedules, including the paged model — slow tier
@pytest.mark.slow
@pytest.mark.parametrize("seed", list(range(10, 17)))
def test_race_harness_sweep(seed, tmp_path):
    _drive_gateway_schedule(seed, tmp_path, n_per_tenant=10)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [21, 22, 23])
def test_race_harness_paged_sweep(paged_pair, tmp_path, seed):
    gen, gen2 = paged_pair
    inj = FaultInjector(spec="sync.preempt=0.25", seed=seed)
    prev = install(inj)
    sync.enable_preemption(inj)
    try:
        gw = Gateway(n_slots=2, max_new_tokens=OUT,
                     journal_path=str(tmp_path / f"ps-{seed}.jsonl"),
                     check_invariants=True)
        gw.load_model("m", "1", instance=gen)
        gw.serve()
        rng = np.random.RandomState(seed)
        reqs = []

        def client(base):
            r = np.random.RandomState(base)
            for _ in range(6):
                reqs.append(gw.submit(
                    "m", r.randint(2, V, r.randint(3, SRC + 1))))

        ts = [threading.Thread(target=client, args=(seed + i,))
              for i in range(2)]
        for t in ts:
            t.start()
        gw.swap_model("m", "2", instance=gen2)
        for t in ts:
            t.join(120)
        for r in list(reqs):
            assert r.wait(180)
        gw.shutdown(drain=True)
        for g in (gen, gen2):
            g.alloc.check_invariants()
            assert g.alloc.stats()["in_use"] == 0
        assert gw.journal.pending() == []
        assert all(r.error is None for r in reqs)
        gw.unload_model("m")
        _ = rng
    finally:
        install(prev)
        sync.disable_preemption()
