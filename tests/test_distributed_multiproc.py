"""Multi-process distributed tests (VERDICT r2 missing#2 / next#3).

Where the reference left its Fluid distributed tests out of CI entirely
(`notest_dist_*.py`, SURVEY.md §4) and tested the Go master only
in-process, these run REAL separate worker processes on CPU:

  * launcher + jax.distributed: 2 processes join one coordination-service
    job and run a cross-process collective;
  * HTTP master: workers in other processes lease tasks; a worker killed
    mid-lease (SIGKILL) times out and its chunk re-dispatches to a
    survivor — the Go master's elasticity contract
    (go/master/service.go:313,341,368).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script: str, env_extra=None, timeout=180, nprocs=None):
    """Write `script` to a temp file and run it (optionally through the
    launcher) with a CPU-only JAX env."""
    import tempfile

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    # the sandbox's TPU-tunnel sitecustomize (see conftest.py) initializes
    # PJRT at interpreter start when its relay is free, which would make
    # the child's jax.distributed.initialize a silent no-op — strip its
    # trigger so CPU children start with uninitialized backends
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra or {})
    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(textwrap.dedent(script))
        path = f.name
    try:
        if nprocs is None:
            cmd = [sys.executable, path]
        else:
            cmd = [sys.executable, "-m", "paddle_tpu.launch",
                   "--nprocs", str(nprocs), path]
        return subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=timeout)
    finally:
        os.unlink(path)


def test_launcher_two_process_collective():
    """2 launcher-spawned processes form one jax.distributed job and a
    cross-process allgather sees both ranks."""
    out = _run("""
        import numpy as np
        from paddle_tpu.parallel import init_distributed
        init_distributed()

        import jax
        from jax.experimental import multihost_utils

        rank = jax.process_index()
        assert jax.process_count() == 2, jax.process_count()
        got = multihost_utils.process_allgather(np.asarray([rank]))
        assert sorted(np.asarray(got).ravel().tolist()) == [0, 1], got
        print(f"rank {rank} OK", flush=True)
    """, nprocs=2)
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert out.stdout.count("OK") == 2, out.stdout


def test_launcher_propagates_failure():
    out = _run("""
        import os, sys
        sys.exit(3 if os.environ["PADDLE_TPU_PROC_ID"] == "1" else 0)
    """, nprocs=2)
    assert out.returncode == 3


WORKER = """
    import json, os, sys, time
    from paddle_tpu.parallel import MasterClient

    addr = sys.argv[1]
    mode = sys.argv[2]                 # "die" or "work"
    client = MasterClient(addr, worker=f"pid-{os.getpid()}")
    seen = []
    while True:
        t = client.get_task()
        if t is None:
            if client.all_done():
                break
            time.sleep(0.05)
            continue
        if mode == "die":
            print(json.dumps({"leased": t.chunk}), flush=True)
            time.sleep(600)            # hold the lease until killed
        seen.append(t.chunk)
        client.task_finished(t.task_id)
    print(json.dumps({"done": seen}), flush=True)
"""


class TestMasterService:
    def _spawn_worker(self, addr, mode):
        import tempfile

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        f = tempfile.NamedTemporaryFile("w", suffix=".py", delete=False)
        f.write(textwrap.dedent(WORKER))
        f.close()
        p = subprocess.Popen([sys.executable, f.name, addr, mode],
                             env=env, stdout=subprocess.PIPE, text=True)
        p._script = f.name
        return p

    def test_cross_process_lease_and_kill_recovery(self):
        """A SIGKILLed worker's chunk re-dispatches to a surviving worker
        process after the lease timeout."""
        from paddle_tpu.parallel import MasterServer, TaskQueue

        queue = TaskQueue(timeout_secs=1.0, failure_max=3)
        queue.set_dataset([[0, 1], [2, 3], [4, 5]])
        server = MasterServer(queue)
        addr = server.start()
        victim = survivor = None
        try:
            victim = self._spawn_worker(addr, "die")
            # wait until the victim holds a lease
            line = victim.stdout.readline()
            leased = json.loads(line)["leased"]
            victim.kill()                       # SIGKILL: no cleanup
            victim.wait()

            survivor = self._spawn_worker(addr, "work")
            out, _ = survivor.communicate(timeout=60)
            done = json.loads(out.strip().splitlines()[-1])["done"]
            # survivor processed every chunk, incl. the dead worker's
            assert sorted(map(tuple, done)) == [(0, 1), (2, 3), (4, 5)]
            assert tuple(leased) in set(map(tuple, done))
            counts = queue.counts()
            assert counts["done"] == 3 and counts["pending"] == 0
        finally:
            for p in (victim, survivor):
                if p is not None:
                    if p.poll() is None:
                        p.kill()
                    os.unlink(p._script)
            server.stop()

    def test_client_reader_integration(self):
        """master_reader over a MasterClient (cross-process protocol, in
        one process) behaves like the in-process queue reader."""
        from paddle_tpu.parallel import (MasterClient, MasterServer,
                                         TaskQueue, master_reader)

        queue = TaskQueue(timeout_secs=5.0)
        queue.set_dataset([[1, 2], [3], [4, 5, 6]])
        server = MasterServer(queue)
        addr = server.start()
        try:
            client = MasterClient(addr, worker="w0")
            reader = master_reader(client, lambda chunk: list(chunk))
            got = sorted(reader())
            assert got == [1, 2, 3, 4, 5, 6]
            assert client.all_done()
            assert client.counts()["done"] == 3
        finally:
            server.stop()

    def test_set_dataset_rejects_bad_chunks_remotely(self):
        from paddle_tpu.parallel import MasterClient, MasterServer, TaskQueue

        server = MasterServer(TaskQueue())
        addr = server.start()
        try:
            client = MasterClient(addr)
            # NaN survives the client's JSON encoding (Python json emits
            # bare NaN) but the queue's allow_nan=False contract rejects it
            with pytest.raises(RuntimeError):
                client.set_dataset([[float("nan")]])
        finally:
            server.stop()


def test_two_process_data_parallel_training():
    """END-TO-END SPMD training across two real processes: each process
    holds 4 virtual CPU devices, the global mesh spans all 8, and the
    executor's dp sharding makes the SPMD partitioner emit the
    cross-process gradient all-reduce (the capability the reference
    needed pserver/NCCL + gRPC for).  Losses must agree bit-for-bit on
    both ranks every step."""
    out = _run("""
        import os
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        import numpy as np
        from paddle_tpu.parallel import init_distributed
        init_distributed()

        import jax
        assert jax.process_count() == 2
        assert len(jax.devices()) == 8          # global view

        from paddle_tpu import fluid, parallel

        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        scope = fluid.Scope()
        with fluid.program_guard(main, startup), fluid.unique_name.guard():
            x = fluid.layers.data("x", [13], "float32")
            y = fluid.layers.data("y", [1], "float32")
            pred = fluid.layers.fc(input=x, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)

        mesh = parallel.make_mesh({"dp": 8}, jax.devices())
        exe = fluid.Executor(fluid.TPUPlace(0))
        rng = np.random.RandomState(0)          # same data on both ranks
        xv = rng.rand(32, 13).astype(np.float32)
        yv = (xv.sum(1, keepdims=True) * 0.25).astype(np.float32)
        losses = []
        with parallel.mesh_guard(mesh), fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(5):
                l, = exe.run(main, feed={"x": xv, "y": yv},
                             fetch_list=[loss])
                losses.append(float(np.asarray(l)))
        assert losses[-1] < losses[0], losses

        from jax.experimental import multihost_utils

        both = multihost_utils.process_allgather(
            np.asarray(losses, np.float64))
        both = np.asarray(both).reshape(2, -1)
        np.testing.assert_array_equal(both[0], both[1])
        print("rank", jax.process_index(), "losses agree:",
              [round(v, 6) for v in losses], flush=True)
    """, nprocs=2, timeout=300)
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert out.stdout.count("losses agree") == 2, out.stdout


def test_hosts_mode_collective():
    """--hosts localhost,localhost (the reference cluster_train/paddle.py
    analog) wires global ranks across 'hosts'; CI uses local spawns, a
    real cluster swaps in ssh."""
    import tempfile

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(textwrap.dedent("""
            import os
            import numpy as np
            from paddle_tpu.parallel import init_distributed
            init_distributed()
            import jax
            from jax.experimental import multihost_utils
            assert jax.process_count() == 2
            hid = int(os.environ["PADDLE_TPU_HOST_ID"])
            got = multihost_utils.process_allgather(np.asarray([hid]))
            assert sorted(np.asarray(got).ravel().tolist()) == [0, 1]
            print("host", hid, "OK", flush=True)
        """))
        path = f.name
    try:
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.launch",
             "--hosts", "localhost,localhost", "--nprocs-per-host", "1",
             path],
            env=env, capture_output=True, text=True, timeout=180)
        assert out.returncode == 0, (out.stdout, out.stderr)
        assert out.stdout.count("OK") == 2, out.stdout
    finally:
        os.unlink(path)


TP_BODY = """
    import os
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import numpy as np
    SINGLE = os.environ.get("TP_SINGLE") == "1"
    if not SINGLE:
        from paddle_tpu.parallel import init_distributed
        init_distributed()

    import jax
    if SINGLE:
        jax.config.update("jax_platforms", "cpu")
    import jax
    from paddle_tpu import fluid, parallel
    from paddle_tpu.fluid import ParamAttr

    ndev = 4 if SINGLE else 8
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 21
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data("x", [16], "float32")
        y = fluid.layers.data("y", [1], "float32")
        h = fluid.layers.fc(
            input=x, size=32, act="relu",
            param_attr=ParamAttr(sharding=(None, "mp")))
        pred = fluid.layers.fc(input=h, size=1,
                               param_attr=ParamAttr(sharding=("mp", None)))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)

    mesh = parallel.make_mesh({"dp": ndev // 2, "mp": 2},
                              jax.devices()[:ndev])
    exe = fluid.Executor(fluid.TPUPlace(0))
    rng = np.random.RandomState(4)
    xv = rng.rand(16, 16).astype(np.float32)
    yv = (xv.sum(1, keepdims=True) * 0.1).astype(np.float32)
    losses = []
    with parallel.mesh_guard(mesh), fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(6):
            l, = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
            losses.append(float(np.asarray(l)))
    assert losses[-1] < losses[0], losses
    print("TP_LOSSES", [round(v, 6) for v in losses], flush=True)
"""


def test_two_process_tensor_parallel_training():
    """dp x mp mesh SPANNING TWO PROCESSES (r3 VERDICT missing#6: mp only
    ever ran on single-process meshes): the hidden layer is column-sharded
    over 'mp', so the partitioner's activation collectives cross the
    process boundary.  Loss trajectory must match a single-process run of
    the same program (same seeds/data) on a dp2 x mp2 mesh."""
    import re

    out = _run(TP_BODY, nprocs=2, timeout=300)
    assert out.returncode == 0, (out.stdout, out.stderr)
    # both ranks write to one pipe: lines can interleave mid-line, so
    # match the bracketed loss lists themselves
    multi = [json.loads(m) for m in
             re.findall(r"\[[0-9eE.,\-\s]+\]", out.stdout)]
    assert len(multi) == 2, out.stdout
    np.testing.assert_array_equal(multi[0], multi[1])  # ranks agree

    single = _run(TP_BODY, env_extra={"TP_SINGLE": "1"}, timeout=300)
    assert single.returncode == 0, (single.stdout, single.stderr)
    ref = json.loads(re.findall(r"\[[0-9eE.,\-\s]+\]",
                                single.stdout)[0])
    np.testing.assert_allclose(multi[0], ref, rtol=1e-4, atol=1e-6)
