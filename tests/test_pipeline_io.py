"""Async input/execution pipeline (ISSUE 2): device-prefetch DataLoader
(fluid/pipeline_io.py) + pipelined executor dispatch (run_pipeline /
run_steps).  The contract under test is the acceptance criterion: the
pipelined paths are NUMERICALLY IDENTICAL to the synchronous
feed->step->fetch loop — prefetch and deferred fetch change scheduling,
never values."""

import time

import numpy as np
import pytest

from paddle_tpu import fluid


def _build_tiny(seed=5):
    """Tiny fixed-seed regression net: fc -> square_error -> SGD.
    Per-program rng salts mean two builds of this model produce the
    SAME init stream (what makes bitwise comparison meaningful)."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data("x", [4], "float32")
        y = fluid.layers.data("y", [1], "float32")
        pred = fluid.layers.fc(input=x, size=1)
        cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(cost)
    return main, startup, scope, cost


def _batches(n=6, bs=8, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.rand(bs, 4).astype(np.float32),
             "y": rng.rand(bs, 1).astype(np.float32)} for _ in range(n)]


def _sync_losses(batches):
    main, startup, scope, cost = _build_tiny()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        return [exe.run(main, feed=f, fetch_list=[cost])[0]
                for f in batches]


# -- DataLoader ------------------------------------------------------------

def test_dataloader_yields_device_feeds_in_order():
    import jax

    batches = _batches()
    loader = fluid.DataLoader(lambda: iter(batches), capacity=2)
    got = list(loader)
    assert len(got) == len(batches)
    for feed, ref in zip(got, batches):
        assert set(feed) == {"x", "y"}
        assert isinstance(feed["x"], jax.Array)
        np.testing.assert_array_equal(np.asarray(feed["x"]), ref["x"])
        np.testing.assert_array_equal(np.asarray(feed["y"]), ref["y"])


def test_dataloader_feeder_conversion_matches_direct():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data("x", [4], "float32")
        y = fluid.layers.data("y", [1], "float32")
    feeder = fluid.DataFeeder([x, y])
    rows = [([0.1, 0.2, 0.3, 0.4], [1.0]), ([0.5, 0.6, 0.7, 0.8], [0.0])]
    direct = feeder.feed(rows)
    loader = feeder.decorate_reader(lambda: iter([rows]))
    (piped,) = list(loader)
    for name in direct:
        np.testing.assert_array_equal(np.asarray(piped[name]),
                                      np.asarray(direct[name]))


def test_dataloader_propagates_reader_error():
    batches = _batches(2)

    def bad_reader():
        yield batches[0]
        raise ValueError("poison batch")

    loader = fluid.DataLoader(bad_reader, capacity=2)
    it = iter(loader)
    next(it)                       # the good batch arrives first
    with pytest.raises(ValueError, match="poison batch"):
        next(it)


def test_dataloader_restarts_reader_per_epoch():
    batches = _batches(3)
    calls = []

    def reader():
        calls.append(1)
        return iter(batches)

    loader = fluid.DataLoader(reader, capacity=2)
    assert len(list(loader)) == 3
    assert len(list(loader)) == 3
    assert len(calls) == 2


def test_dataloader_rejects_non_dict():
    loader = fluid.DataLoader(lambda: iter([[1, 2, 3]]), capacity=1)
    with pytest.raises(TypeError, match="feed dicts"):
        list(loader)


def test_layers_io_shims():
    from paddle_tpu.fluid.layers.io import double_buffer, py_reader

    batches = _batches(2)
    dl = py_reader(capacity=3, reader=lambda: iter(batches))
    assert isinstance(dl, fluid.DataLoader)
    assert dl.capacity == 3
    assert len(list(dl)) == 2
    assert double_buffer(dl) is dl        # already a loader: no rewrap
    dl2 = double_buffer(lambda: iter(batches))
    assert len(list(dl2)) == 2


# -- pipelined execution ---------------------------------------------------

def test_run_pipeline_bitwise_identical_to_sync():
    """The ISSUE-2 smoke criterion: pipelined loop == synchronous run()
    loop, bit for bit, on a fixed-seed tiny model."""
    batches = _batches()
    sync = _sync_losses(batches)

    main, startup, scope, cost = _build_tiny()
    exe = fluid.Executor(fluid.CPUPlace())
    loader = fluid.DataLoader(lambda: iter(batches), capacity=3)
    with fluid.scope_guard(scope):
        exe.run(startup)
        piped = exe.run_pipeline(main, loader, fetch_list=[cost],
                                 fetch_every=4)
    assert len(piped) == len(sync)
    for s, p in zip(sync, piped):
        np.testing.assert_array_equal(np.asarray(s), np.asarray(p[0]))


def test_run_pipeline_on_fetch_streams():
    batches = _batches(5)
    main, startup, scope, cost = _build_tiny()
    exe = fluid.Executor(fluid.CPUPlace())
    seen = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        n = exe.run_pipeline(main, fluid.DataLoader(lambda: iter(batches)),
                             fetch_list=[cost], fetch_every=2,
                             on_fetch=seen.append)
    assert n == 5
    assert len(seen) == 5
    np.testing.assert_array_equal(np.asarray(seen[0][0]),
                                  np.asarray(_sync_losses(batches)[0]))


def test_run_pipeline_accepts_plain_iterables():
    batches = _batches(3)
    sync = _sync_losses(batches)
    main, startup, scope, cost = _build_tiny()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        piped = exe.run_pipeline(main, iter(batches), fetch_list=[cost])
    for s, p in zip(sync, piped):
        np.testing.assert_array_equal(np.asarray(s), np.asarray(p[0]))


def test_run_steps_matches_sequential():
    """Multi-step-per-dispatch (lax.scan over stacked feeds): same
    losses AND same final parameters as k sequential run() calls."""
    batches = _batches()
    sync = _sync_losses(batches)

    main, startup, scope, cost = _build_tiny()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        stepped = exe.run_steps(main, feeds=batches, fetch_list=[cost])
    assert len(stepped) == len(sync)
    for s, p in zip(sync, stepped):
        np.testing.assert_allclose(np.asarray(s), np.asarray(p[0]),
                                   rtol=1e-6, atol=1e-7)

    # final parameter state matches the sequential loop's
    main2, startup2, scope2, cost2 = _build_tiny()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope2):
        exe2.run(startup2)
        for f in batches:
            exe2.run(main2, feed=f, fetch_list=[cost2])
    for p in main.global_block().all_parameters():
        np.testing.assert_allclose(np.asarray(scope.find_var(p.name)),
                                   np.asarray(scope2.find_var(p.name)),
                                   rtol=1e-6, atol=1e-7)


def test_run_steps_advances_scope_rng_like_sequential():
    batches = _batches(4)
    main, startup, scope, cost = _build_tiny()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run_steps(main, feeds=batches, fetch_list=[cost])
    rng_scan = scope._rng_step

    main2, startup2, scope2, cost2 = _build_tiny()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope2):
        exe2.run(startup2)
        for f in batches:
            exe2.run(main2, feed=f, fetch_list=[cost2])
    assert rng_scan == scope2._rng_step


def test_run_steps_rejects_mismatched_signatures():
    main, startup, scope, cost = _build_tiny()
    exe = fluid.Executor(fluid.CPUPlace())
    feeds = _batches(2) + _batches(1, bs=16)
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(ValueError, match="signature differs"):
            exe.run_steps(main, feeds=feeds, fetch_list=[cost])


def test_run_steps_empty_feeds():
    main, startup, scope, cost = _build_tiny()
    exe = fluid.Executor(fluid.CPUPlace())
    assert exe.run_steps(main, feeds=[], fetch_list=[cost]) == []


# -- trainer wiring --------------------------------------------------------

def test_v2_trainer_prefetch_matches_sync():
    """Both v2 front-end paths (prefetch DataLoader vs inline feeder)
    must produce identical per-iteration costs."""
    import paddle_tpu.v2 as paddle

    def run_v2(prefetch):
        paddle.init(use_gpu=False, trainer_count=1, seed=7)
        images = paddle.layer.data(
            name="x", type=paddle.data_type.dense_vector(4))
        label = paddle.layer.data(
            name="y", type=paddle.data_type.integer_value(2))
        fc = paddle.layer.fc(input=images, size=2,
                             act=paddle.activation.Softmax())
        cost = paddle.layer.classification_cost(input=fc, label=label)
        params = paddle.parameters.create(cost)
        trainer = paddle.trainer.SGD(
            cost=cost, parameters=params,
            update_equation=paddle.optimizer.Momentum(learning_rate=0.1))
        rows = [(list(np.linspace(0, 1, 4) + i * 0.01), i % 2)
                for i in range(32)]

        def reader():
            for i in range(0, 32, 8):
                yield rows[i:i + 8]

        costs = []

        def handler(evt):
            if isinstance(evt, paddle.event.EndIteration):
                costs.append(evt.cost)

        trainer.train(reader, num_passes=2, event_handler=handler,
                      prefetch=prefetch)
        return costs

    sync = run_v2(prefetch=0)
    piped = run_v2(prefetch=2)
    assert len(sync) == len(piped) == 8
    np.testing.assert_array_equal(np.asarray(sync), np.asarray(piped))


def test_resilient_trainer_prefetch_trains_identically(tmp_path):
    """ResilientTrainer(prefetch=N) consumes the same records in the
    same order and settles leases the same way as the inline reader."""
    from paddle_tpu.parallel.master import TaskQueue
    from paddle_tpu.resilience import ResilientTrainer

    def run(prefetch, subdir):
        q = TaskQueue(timeout_secs=30)
        q.set_dataset(["c0", "c1", "c2"])
        seen = []
        trainer = ResilientTrainer(
            str(tmp_path / subdir), q,
            read_chunk=lambda c: [f"{c}:{i}" for i in range(4)],
            prefetch=prefetch)
        trainer.run(lambda rec, step: seen.append(rec))
        return seen

    assert run(0, "sync") == run(3, "piped")


def test_resilient_trainer_prefetch_read_error_charges_failure(tmp_path):
    from paddle_tpu.parallel.master import TaskQueue
    from paddle_tpu.resilience import ResilientTrainer

    q = TaskQueue(timeout_secs=30, failure_max=1)
    q.set_dataset(["c0"])

    def read_chunk(chunk):
        yield "ok"
        raise IOError("mid-chunk read failure")

    seen = []
    trainer = ResilientTrainer(str(tmp_path / "ckpt"), q,
                               read_chunk=read_chunk, prefetch=2)
    trainer.run(lambda rec, step: seen.append(rec))
    # the good record trained; the failure burned the chunk's budget
    # (failure_max=1 discards it) instead of looking like a short chunk
    assert seen == ["ok", "ok"] or seen == ["ok"]
    assert q.all_done()


# -- throughput (slow) -----------------------------------------------------

@pytest.mark.slow
def test_pipelined_feed_no_slower_than_sync():
    """Throughput guard: with real host-side data prep in the reader
    (the thing prefetch exists to hide), the pipelined loop must not
    lose to the synchronous feed->step->fetch loop.  Generous 1.5x
    slack: CI boxes jitter (observed 3x wall swings between trials),
    the CPU backend has no true async H2D to overlap, and the win
    grows with transfer cost on hardware.  (A
    microsecond-scale model with zero data prep is deliberately NOT
    tested — there per-batch thread handoff dominates and pipelining
    has nothing to hide.)"""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 5
    startup.random_seed = 5
    scope = fluid.Scope()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data("x", [256], "float32")
        y = fluid.layers.data("y", [1], "float32")
        h = fluid.layers.fc(input=x, size=256, act="relu")
        pred = fluid.layers.fc(input=h, size=1)
        cost = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(cost)

    n, bs = 30, 256

    def make_batch(i):
        rng = np.random.RandomState(i)
        xv = rng.rand(bs, 256).astype(np.float32)
        xv = (xv - xv.mean(axis=1, keepdims=True)) \
            / (xv.std(axis=1, keepdims=True) + 1e-6)
        return {"x": xv, "y": rng.rand(bs, 1).astype(np.float32)}

    def reader():
        for i in range(n):
            yield make_batch(i)

    exe = fluid.Executor(fluid.CPUPlace())
    sync_dt = piped_dt = float("inf")
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=make_batch(0), fetch_list=[cost])  # compile
        loader = fluid.DataLoader(reader, capacity=4)
        # best-of-5 each: a loaded CI box stalls either loop for whole
        # scheduler quanta (observed 3x wall-time swings between
        # back-to-back trials); the comparison needs the unstalled times
        for _ in range(5):
            t0 = time.perf_counter()
            for f in reader():
                out, = exe.run(main, feed=f, fetch_list=[cost],
                               return_numpy=False)
                float(np.asarray(out))
            sync_dt = min(sync_dt, time.perf_counter() - t0)

            t0 = time.perf_counter()
            exe.run_pipeline(main, loader, fetch_list=[cost],
                             fetch_every=8)
            piped_dt = min(piped_dt, time.perf_counter() - t0)
    assert piped_dt <= sync_dt * 1.5, (piped_dt, sync_dt)
