// Shared reader for the checkpoint tensor wire format (fluid/io.py
// frame_bytes + _tensor_bytes): MAGIC2 framing with a crc32 trailer, then
// [u32 header_len][json header {dtype, shape, lod, batch}][raw data]
// (+ int32 lengths tail for lod tensors).  Used by both the desc-walking C
// inference engine (capi.cc) and the StableHLO/PJRT runner
// (pjrt_runner.cc), which needs the bytes dtype-preserved for device
// upload.  Reference analog: the LoDTensor stream deserializer in
// operators/load_op.cc + framework/lod_tensor.cc (version + dims + dtype +
// lod + raw bytes).

#ifndef PTPU_TENSOR_FILE_H_
#define PTPU_TENSOR_FILE_H_

#include <zlib.h>

#include <cstring>
#include <fstream>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

#include "json.h"

namespace ptpu {

inline std::string read_file(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) throw std::runtime_error("cannot open " + path);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

// MAGIC2 + payload + crc32le trailer (fluid/io.py frame_bytes)
inline std::string unframe(const std::string& data, const std::string& what) {
  static const char kMagic2[] = "PDTPU\x02";
  const size_t mlen = 6;
  if (data.size() < mlen + 4 ||
      std::memcmp(data.data(), kMagic2, mlen) != 0)
    throw std::runtime_error(what + ": bad magic/too short");
  std::string payload = data.substr(mlen, data.size() - mlen - 4);
  uint32_t want;
  std::memcpy(&want, data.data() + data.size() - 4, 4);
  uint32_t got = crc32(0, (const Bytef*)payload.data(), payload.size());
  if (got != want)
    throw std::runtime_error(what + ": crc mismatch (corrupt file)");
  return payload;
}

inline int64_t dtype_width(const std::string& dtype) {
  if (dtype == "float64" || dtype == "int64") return 8;
  if (dtype == "float32" || dtype == "int32") return 4;
  if (dtype == "bfloat16" || dtype == "float16") return 2;
  if (dtype == "int8" || dtype == "uint8" || dtype == "bool") return 1;
  throw std::runtime_error("unsupported tensor dtype " + dtype);
}

struct RawTensor {
  std::string dtype;
  std::vector<int64_t> shape;
  std::string data;              // raw little-endian bytes, dtype-preserved
  std::vector<int32_t> lengths;  // per-row lengths when lod
};

// parse one framed-payload tensor, keeping the on-disk dtype
inline RawTensor parse_tensor_raw(const std::string& payload,
                                  const std::string& what) {
  if (payload.size() < 4) throw std::runtime_error(what + ": truncated");
  uint32_t hlen;
  std::memcpy(&hlen, payload.data(), 4);
  if (payload.size() < 4 + (size_t)hlen)
    throw std::runtime_error(what + ": header length exceeds payload");
  const std::string header_text = payload.substr(4, hlen);
  JsonParser jp(header_text);  // parser keeps a reference — must outlive it
  JsonPtr h = jp.parse();
  RawTensor t;
  t.dtype = h->at("dtype")->s;
  int64_t n = 1;
  for (auto& e : h->at("shape")->arr) {
    if (e->i < 0) throw std::runtime_error(what + ": negative dim");
    t.shape.push_back(e->i);
    if (e->i != 0 && n > ((int64_t)1 << 40) / e->i)
      throw std::runtime_error(what + ": shape product overflow");
    n *= e->i;
  }
  int64_t w = dtype_width(t.dtype);
  size_t avail = payload.size() - 4 - hlen;
  if (avail < (size_t)(n * w))
    throw std::runtime_error(what + ": short data");
  t.data.assign(payload.data() + 4 + hlen, (size_t)(n * w));
  if (h->get("lod") && h->at("lod")->b) {
    int64_t batch = h->at("batch")->i;
    if (avail < (size_t)(n * w) + (size_t)batch * 4)
      throw std::runtime_error(what + ": short lengths tail");
    t.lengths.resize(batch);
    std::memcpy(t.lengths.data(), payload.data() + 4 + hlen + n * w,
                (size_t)batch * 4);
  }
  return t;
}

}  // namespace ptpu

#endif  // PTPU_TENSOR_FILE_H_
