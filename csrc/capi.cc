// Native inference engine + C ABI — the TPU build's counterpart of the
// reference's embedding story: paddle/capi/gradient_machine.h:36
// (paddle_gradient_machine_create_for_inference), :73 (..._forward) and the
// C++ model loader paddle/inference/io.h:32 (Load).
//
// A saved `save_inference_model` directory (framed JSON ProgramDesc in
// `__model__` + CRC-framed tensor files per persistable var) is loaded and
// executed HERE, in plain C++, with no Python anywhere in the process —
// the test drives this through ctypes from a clean interpreter, but any C
// program can link it.  Where the reference interpreted a ModelConfig with
// the gserver layer engine, this walks the (pruned, feed/fetch-annotated)
// program desc with float32 CPU kernels: the right native analog for
// host-side/embedded serving.  The TPU serving tier is pjrt_runner.cc
// (same ABI, StableHLO through the PJRT C API).

#include <algorithm>
#include <cmath>
#include <functional>
#include <limits>
#include <cstring>
#include <map>
#include <memory>
#include <numeric>
#include <string>
#include <vector>

#include "desc.h"
#include "tensor_file.h"

namespace ptpu {
namespace {

// -- tensors ----------------------------------------------------------------
// framing/parse shared with the PJRT runner (tensor_file.h); this engine
// computes in float32, so the raw dtype-preserved bytes convert here.

using ptpu::read_file;
using ptpu::unframe;

struct Tensor {
  std::vector<int64_t> shape;
  std::vector<float> data;
  std::vector<int32_t> lengths;   // per-row valid lengths when a sequence

  int64_t numel() const {
    int64_t n = 1;
    for (auto d : shape) n *= d;
    return n;
  }
};

Tensor from_raw(const ptpu::RawTensor& r, const std::string& what) {
  Tensor t;
  t.shape = r.shape;
  t.lengths = r.lengths;
  int64_t n = t.numel();
  t.data.resize(n);
  const char* raw = r.data.data();
  if (r.dtype == "float32") {
    std::memcpy(t.data.data(), raw, n * 4);
  } else if (r.dtype == "float64") {
    for (int64_t i = 0; i < n; ++i) {
      double v;
      std::memcpy(&v, raw + i * 8, 8);
      t.data[i] = (float)v;
    }
  } else if (r.dtype == "int64") {
    for (int64_t i = 0; i < n; ++i) {
      int64_t v;
      std::memcpy(&v, raw + i * 8, 8);
      t.data[i] = (float)v;
    }
  } else if (r.dtype == "int32") {
    for (int64_t i = 0; i < n; ++i) {
      int32_t v;  // read at native width so negatives sign-extend
      std::memcpy(&v, raw + i * 4, 4);
      t.data[i] = (float)v;
    }
  } else if (r.dtype == "int8") {
    // quantized weights (io.py PTQ artifacts): the raw int8 VALUES are
    // kept — dequantization is the quantized_* op's job (out * scale),
    // exactly as on the XLA tier
    for (int64_t i = 0; i < n; ++i)
      t.data[i] = (float)(int8_t)raw[i];
  } else {
    throw std::runtime_error(what + ": unsupported dtype " + r.dtype +
                             " (native serving engine is float32)");
  }
  return t;
}

Tensor parse_tensor(const std::string& payload, const std::string& what) {
  return from_raw(ptpu::parse_tensor_raw(payload, what), what);
}

// -- kernels ----------------------------------------------------------------

void matmul2d(const float* x, const float* y, float* out, int64_t m,
              int64_t k, int64_t n) {
  for (int64_t i = 0; i < m; ++i) {
    for (int64_t j = 0; j < n; ++j) out[i * n + j] = 0.f;
    for (int64_t p = 0; p < k; ++p) {
      float xv = x[i * k + p];
      if (xv == 0.f) continue;
      const float* yr = y + p * n;
      float* orow = out + i * n;
      for (int64_t j = 0; j < n; ++j) orow[j] += xv * yr[j];
    }
  }
}

struct Engine {
  // desc + loaded weights are IMMUTABLE and shared between clones
  // (ptpu_clone_shared — the analog of the reference's
  // paddle_gradient_machine_create_shared_param, capi/gradient_machine.h:88):
  // each clone carries only its own activation map, so N serving threads
  // share one copy of the model and never contend.
  std::shared_ptr<const ProgramDesc> prog;
  std::shared_ptr<const std::map<std::string, Tensor>> params;
  std::map<std::string, Tensor> vars;   // feeds + activations, per handle
  std::vector<std::string> feed_names, fetch_names;
  std::vector<Tensor> outputs;

  const BlockDesc& block() const { return prog->blocks.at(0); }

  Tensor& in(const OpDesc& op, const char* slot, int i = 0) {
    auto it = op.inputs.find(slot);
    if (it == op.inputs.end() || (int)it->second.size() <= i)
      throw std::runtime_error(op.type + ": missing input slot " + slot);
    auto v = vars.find(it->second[i]);
    if (v != vars.end()) return v->second;
    auto p = params->find(it->second[i]);
    if (p != params->end())
      // kernels never mutate inputs (outputs are always fresh tensors),
      // so handing out a non-const ref to the shared weights is safe
      return const_cast<Tensor&>(p->second);
    throw std::runtime_error(op.type + ": input var " + it->second[i] +
                             " not computed yet");
  }
  bool has_in(const OpDesc& op, const char* slot) {
    auto it = op.inputs.find(slot);
    return it != op.inputs.end() && !it->second.empty() &&
           (vars.count(it->second[0]) || params->count(it->second[0]));
  }
  // name -> tensor across both maps (activations shadow weights), for
  // kernels that walk variadic input lists directly
  const Tensor* find_tensor(const std::string& name) const {
    auto v = vars.find(name);
    if (v != vars.end()) return &v->second;
    auto p = params->find(name);
    if (p != params->end()) return &p->second;
    return nullptr;
  }
  Tensor& out(const OpDesc& op, const char* slot = "Out", int i = 0) {
    return vars[op.outputs.at(slot).at(i)];
  }

  void run_op(const OpDesc& op);
  void forward();
};

void Engine::run_op(const OpDesc& op) {
  const std::string& t = op.type;
  if (t == "feed" || t == "fetch") return;  // handled by forward()
  if (t == "mul" || t == "quantized_mul") {
    Tensor& x = in(op, "X");
    Tensor& y = in(op, "Y");
    int64_t xnum = op.attr_int("x_num_col_dims", 1);
    int64_t ynum = op.attr_int("y_num_col_dims", 1);
    int64_t m = 1, k = 1, k2 = 1, n = 1;
    for (size_t i = 0; i < x.shape.size(); ++i)
      ((int64_t)i < xnum ? m : k) *= x.shape[i];
    for (size_t i = 0; i < y.shape.size(); ++i)
      ((int64_t)i < ynum ? k2 : n) *= y.shape[i];
    if (k != k2)
      throw std::runtime_error(t + ": inner dim mismatch");
    Tensor r;
    r.shape.assign(x.shape.begin(), x.shape.begin() + xnum);
    r.shape.insert(r.shape.end(), y.shape.begin() + ynum, y.shape.end());
    r.data.resize(m * n);
    matmul2d(x.data.data(), y.data.data(), r.data.data(), m, k, n);
    if (t == "quantized_mul") {
      // the int8 weight loaded as raw quantized values; fold the
      // per-output-channel (or scalar) fp32 scale into the result —
      // the same dequant-into-output-scale the XLA emitter does
      Tensor& sc = in(op, "Scale");
      if (sc.numel() != 1 && sc.numel() != n)
        throw std::runtime_error("quantized_mul: Scale has " +
                                 std::to_string(sc.numel()) +
                                 " elements, want 1 or " +
                                 std::to_string(n));
      for (int64_t i = 0; i < m; ++i)
        for (int64_t j = 0; j < n; ++j)
          r.data[i * n + j] *= sc.data[sc.numel() == 1 ? 0 : j];
    }
    out(op) = std::move(r);
  } else if (t == "elementwise_add" || t == "elementwise_sub" ||
             t == "elementwise_mul" || t == "elementwise_div") {
    Tensor& x = in(op, "X");
    Tensor& y = in(op, "Y");
    int64_t axis = op.attr_int("axis", -1);
    int64_t xr = (int64_t)x.shape.size(), yr = (int64_t)y.shape.size();
    if (axis < 0) axis = xr - yr;
    int64_t mid = y.numel(), inner = 1;
    for (int64_t i = axis + yr; i < xr; ++i) inner *= x.shape[i];
    int64_t outer = x.numel() / (mid * inner);
    Tensor r;
    r.shape = x.shape;
    r.data.resize(x.numel());
    char k = t[12];  // a/s/m/d — add/sub/mul(div share 'm'? no: 'd')
    for (int64_t o = 0; o < outer; ++o)
      for (int64_t mi = 0; mi < mid; ++mi) {
        float yv = y.data[mi];
        const float* xp = x.data.data() + (o * mid + mi) * inner;
        float* rp = r.data.data() + (o * mid + mi) * inner;
        for (int64_t i = 0; i < inner; ++i)
          rp[i] = k == 'a' ? xp[i] + yv
                : k == 's' ? xp[i] - yv
                : k == 'm' ? xp[i] * yv
                           : xp[i] / yv;
      }
    out(op) = std::move(r);
  } else if (t == "relu" || t == "tanh" || t == "sigmoid" || t == "exp" ||
             t == "sqrt" || t == "abs") {
    Tensor& x = in(op, "X");
    Tensor r;
    r.shape = x.shape;
    r.data.resize(x.numel());
    for (int64_t i = 0; i < x.numel(); ++i) {
      float v = x.data[i];
      r.data[i] = t == "relu"    ? (v > 0 ? v : 0)
                  : t == "tanh"  ? std::tanh(v)
                  : t == "sigmoid" ? 1.f / (1.f + std::exp(-v))
                  : t == "exp"   ? std::exp(v)
                  : t == "sqrt"  ? std::sqrt(v)
                                 : std::fabs(v);
    }
    out(op) = std::move(r);
  } else if (t == "softmax") {
    Tensor& x = in(op, "X");
    int64_t n = x.shape.back(), rows = x.numel() / n;
    Tensor r;
    r.shape = x.shape;
    r.data.resize(x.numel());
    for (int64_t i = 0; i < rows; ++i) {
      const float* xp = x.data.data() + i * n;
      float* rp = r.data.data() + i * n;
      float mx = xp[0];
      for (int64_t j = 1; j < n; ++j) mx = std::max(mx, xp[j]);
      float s = 0;
      for (int64_t j = 0; j < n; ++j) s += (rp[j] = std::exp(xp[j] - mx));
      for (int64_t j = 0; j < n; ++j) rp[j] /= s;
    }
    out(op) = std::move(r);
  } else if (t == "scale") {
    Tensor& x = in(op, "X");
    float sc = (float)op.attr_num("scale", 1.0);
    float b = (float)op.attr_num("bias", 0.0);
    bool after = op.attr_bool("bias_after_scale", true);
    Tensor r;
    r.shape = x.shape;
    r.data.resize(x.numel());
    for (int64_t i = 0; i < x.numel(); ++i)
      r.data[i] = after ? x.data[i] * sc + b : (x.data[i] + b) * sc;
    out(op) = std::move(r);
  } else if (t == "reshape") {
    Tensor& x = in(op, "X");
    auto shp = op.attr_ints("shape");
    int64_t known = 1, infer = -1;
    for (size_t i = 0; i < shp.size(); ++i) {
      if (shp[i] == 0) shp[i] = x.shape.at(i);
      if (shp[i] == -1) infer = (int64_t)i;
      else known *= shp[i];
    }
    if (infer >= 0) shp[infer] = x.numel() / known;
    Tensor r;
    r.shape = shp;
    r.data = x.data;
    out(op) = std::move(r);
  } else if (t == "transpose") {
    Tensor& x = in(op, "X");
    auto perm = op.attr_ints("axis");
    int64_t rank = (int64_t)x.shape.size();
    std::vector<int64_t> ns(rank), xstr(rank, 1);
    for (int64_t i = rank - 2; i >= 0; --i)
      xstr[i] = xstr[i + 1] * x.shape[i + 1];
    for (int64_t i = 0; i < rank; ++i) ns[i] = x.shape[perm[i]];
    Tensor r;
    r.shape = ns;
    r.data.resize(x.numel());
    std::vector<int64_t> idx(rank, 0);
    for (int64_t lin = 0; lin < x.numel(); ++lin) {
      int64_t src = 0;
      for (int64_t i = 0; i < rank; ++i) src += idx[i] * xstr[perm[i]];
      r.data[lin] = x.data[src];
      for (int64_t i = rank - 1; i >= 0; --i)
        if (++idx[i] < ns[i]) break; else idx[i] = 0;
    }
    out(op) = std::move(r);
  } else if (t == "mean") {
    Tensor& x = in(op, "X");
    double s = 0;
    for (auto v : x.data) s += v;
    Tensor r;
    r.shape = {};
    r.data = {(float)(s / std::max<int64_t>(1, x.numel()))};
    out(op) = std::move(r);
  } else if (t == "dropout") {
    // inference semantics: identity (upscale-at-train convention)
    out(op) = in(op, "X");
  } else if (t == "batch_norm") {
    Tensor& x = in(op, "X");
    Tensor& scale = in(op, "Scale");
    Tensor& bias = in(op, "Bias");
    Tensor& mean = in(op, "Mean");
    Tensor& var = in(op, "Variance");
    float eps = (float)op.attr_num("epsilon", 1e-5);
    int64_t c = x.shape.size() >= 2 ? x.shape[1] : x.shape.back();
    int64_t inner = x.numel() / (x.shape[0] * c);
    Tensor r;
    r.shape = x.shape;
    r.data.resize(x.numel());
    for (int64_t b = 0; b < x.shape[0]; ++b)
      for (int64_t ch = 0; ch < c; ++ch) {
        float inv = 1.f / std::sqrt(var.data[ch] + eps);
        float sc = scale.data[ch] * inv, sh = bias.data[ch];
        float mu = mean.data[ch];
        const float* xp = x.data.data() + (b * c + ch) * inner;
        float* rp = r.data.data() + (b * c + ch) * inner;
        for (int64_t i = 0; i < inner; ++i)
          rp[i] = (xp[i] - mu) * sc + sh;
      }
    out(op, "Y") = std::move(r);
  } else if (t == "conv2d" || t == "quantized_conv2d") {
    Tensor& x = in(op, "Input");
    Tensor& w = in(op, "Filter");
    // int8 filter loaded as raw quantized values; fold the per-output-
    // channel (or scalar) fp32 scale into each output channel, same as
    // quantized_mul folds it into the matmul result
    const Tensor* sc = nullptr;
    if (t == "quantized_conv2d") {
      sc = &in(op, "Scale");
      if (sc->numel() != 1 && sc->numel() != w.shape[0])
        throw std::runtime_error("quantized_conv2d: Scale has " +
                                 std::to_string(sc->numel()) +
                                 " elements, want 1 or " +
                                 std::to_string(w.shape[0]));
    }
    auto st = op.attr_ints("strides");
    auto pd = op.attr_ints("paddings");
    auto dil = op.attr_ints("dilations");
    int64_t g = op.attr_int("groups", 1);
    if (st.empty()) st = {1, 1};
    if (pd.empty()) pd = {0, 0};
    for (auto d : dil)
      if (d != 1)
        throw std::runtime_error(
            "conv2d: dilations != 1 unsupported in the native engine — "
            "failing loudly instead of computing a dilation-1 conv");
    int64_t B = x.shape[0], C = x.shape[1], H = x.shape[2], W = x.shape[3];
    int64_t O = w.shape[0], CK = w.shape[1], KH = w.shape[2],
            KW = w.shape[3];
    int64_t OH = (H + 2 * pd[0] - KH) / st[0] + 1;
    int64_t OW = (W + 2 * pd[1] - KW) / st[1] + 1;
    Tensor r;
    r.shape = {B, O, OH, OW};
    r.data.assign(B * O * OH * OW, 0.f);
    int64_t opg = O / g, cpg = C / g;
    for (int64_t b = 0; b < B; ++b)
      for (int64_t o = 0; o < O; ++o) {
        int64_t gi = o / opg;
        float oc_scale = sc ? sc->data[sc->numel() == 1 ? 0 : o] : 1.f;
        for (int64_t oh = 0; oh < OH; ++oh)
          for (int64_t ow = 0; ow < OW; ++ow) {
            float acc = 0;
            for (int64_t ck = 0; ck < CK && ck < cpg; ++ck) {
              int64_t c = gi * cpg + ck;
              for (int64_t kh = 0; kh < KH; ++kh) {
                int64_t ih = oh * st[0] - pd[0] + kh;
                if (ih < 0 || ih >= H) continue;
                for (int64_t kw = 0; kw < KW; ++kw) {
                  int64_t iw = ow * st[1] - pd[1] + kw;
                  if (iw < 0 || iw >= W) continue;
                  acc += x.data[((b * C + c) * H + ih) * W + iw] *
                         w.data[((o * CK + ck) * KH + kh) * KW + kw];
                }
              }
            }
            r.data[((b * O + o) * OH + oh) * OW + ow] = acc * oc_scale;
          }
      }
    out(op, "Output") = std::move(r);
  } else if (t == "pool2d") {
    Tensor& x = in(op, "X");
    std::string pt = "max";
    if (op.attrs && op.attrs->get("pooling_type"))
      pt = op.attrs->get("pooling_type")->s;
    auto ks = op.attr_ints("ksize");
    auto st = op.attr_ints("strides");
    auto pd = op.attr_ints("paddings");
    bool global_p = op.attr_bool("global_pooling", false);
    if (op.attr_bool("ceil_mode", false))
      throw std::runtime_error("pool2d: ceil_mode unsupported in the "
                               "native engine");
    int64_t B = x.shape[0], C = x.shape[1], H = x.shape[2], W = x.shape[3];
    if (global_p) {
      ks = {H, W};
      st = {H, W};
      pd = {0, 0};
    }
    if (st.empty()) st = {2, 2};
    if (pd.empty()) pd = {0, 0};
    int64_t OH = (H + 2 * pd[0] - ks[0]) / st[0] + 1;
    int64_t OW = (W + 2 * pd[1] - ks[1]) / st[1] + 1;
    Tensor r;
    r.shape = {B, C, OH, OW};
    r.data.resize(B * C * OH * OW);
    for (int64_t b = 0; b < B; ++b)
      for (int64_t c = 0; c < C; ++c)
        for (int64_t oh = 0; oh < OH; ++oh)
          for (int64_t ow = 0; ow < OW; ++ow) {
            float best = -3.4e38f;
            double sum = 0;
            int64_t cnt = 0;
            for (int64_t kh = 0; kh < ks[0]; ++kh) {
              int64_t ih = oh * st[0] - pd[0] + kh;
              if (ih < 0 || ih >= H) continue;
              for (int64_t kw = 0; kw < ks[1]; ++kw) {
                int64_t iw = ow * st[1] - pd[1] + kw;
                if (iw < 0 || iw >= W) continue;
                float v = x.data[((b * C + c) * H + ih) * W + iw];
                best = std::max(best, v);
                sum += v;
                ++cnt;
              }
            }
            r.data[((b * C + c) * OH + oh) * OW + ow] =
                pt == "max" ? best : (float)(sum / std::max<int64_t>(1, cnt));
          }
    out(op) = std::move(r);
  } else if (t == "lookup_table") {
    // embedding gather (reference lookup_table_op.cc; fluid emitter
    // ops/tensor_ops.py lookup_table) — ids values ride the float store
    // (exact for |id| < 2^24; vocab ids comfortably fit)
    Tensor& w = in(op, "W");
    Tensor& ids = in(op, "Ids");
    int64_t v = w.shape.at(0), d = w.numel() / std::max<int64_t>(1, v);
    std::vector<int64_t> ish = ids.shape;
    if (!ish.empty() && ish.back() == 1) ish.pop_back();
    int64_t n = ids.numel();
    bool has_pad = op.attrs && op.attrs->get("padding_idx");
    int64_t pad = op.attr_int("padding_idx", 0);
    Tensor r;
    r.shape = ish;
    r.shape.push_back(d);
    r.data.assign(n * d, 0.f);
    for (int64_t i = 0; i < n; ++i) {
      int64_t idx = (int64_t)ids.data[i];
      if (has_pad && idx == pad) continue;           // zeros row
      if (idx < 0 || idx >= v)
        throw std::runtime_error("lookup_table: id out of range");
      std::memcpy(r.data.data() + i * d, w.data.data() + idx * d, d * 4);
    }
    r.lengths = ids.lengths;
    out(op) = std::move(r);
  } else if (t == "sequence_pool") {
    // reference sequence_pool_op.cc / fluid ops/sequence_ops.py: reduce
    // the time axis over each row's valid prefix -> dense [batch, ...]
    Tensor& x = in(op, "X");
    std::string ptype = op.attr_str("pooltype", "sum");
    for (auto& c : ptype) c = std::tolower(c);
    if (x.lengths.empty() || x.shape.size() < 2)
      throw std::runtime_error("sequence_pool: input is not a sequence");
    int64_t b = x.shape[0], tt = x.shape[1];
    int64_t inner = x.numel() / std::max<int64_t>(1, b * tt);
    Tensor r, idx;
    r.shape = {b};
    r.shape.insert(r.shape.end(), x.shape.begin() + 2, x.shape.end());
    r.data.assign(b * inner, 0.f);
    idx.shape = r.shape;
    idx.data.assign(b * inner, 0.f);
    for (int64_t i = 0; i < b; ++i) {
      int64_t len = std::min<int64_t>(x.lengths[i], tt);
      const float* row = x.data.data() + i * tt * inner;
      float* rp = r.data.data() + i * inner;
      float* ip = idx.data.data() + i * inner;
      if (ptype == "last" || ptype == "first") {
        int64_t at = ptype == "first" ? 0 : std::max<int64_t>(len - 1, 0);
        std::memcpy(rp, row + at * inner, inner * 4);
        for (int64_t j = 0; j < inner; ++j) ip[j] = (float)at;
      } else if (ptype == "max") {
        for (int64_t j = 0; j < inner; ++j) {
          float best = -3.4e38f;
          int64_t bi = 0;
          for (int64_t s2 = 0; s2 < len; ++s2)
            if (row[s2 * inner + j] > best) {
              best = row[s2 * inner + j];
              bi = s2;
            }
          // empty row: the Python tier's masked max yields -inf
          rp[j] = len ? best
                      : -std::numeric_limits<float>::infinity();
          ip[j] = (float)bi;
        }
      } else if (ptype == "sum" || ptype == "average" || ptype == "sqrt") {
        for (int64_t j = 0; j < inner; ++j) {
          double acc = 0;
          for (int64_t s2 = 0; s2 < len; ++s2) acc += row[s2 * inner + j];
          double div = ptype == "average" ? std::max<int64_t>(len, 1)
                       : ptype == "sqrt"
                           ? std::sqrt((double)std::max<int64_t>(len, 1))
                           : 1.0;
          rp[j] = (float)(acc / div);
        }
      } else {
        throw std::runtime_error("sequence_pool: unsupported pooltype " +
                                 ptype);
      }
    }
    out(op) = std::move(r);
    if (op.outputs.count("MaxIndex")) out(op, "MaxIndex") = std::move(idx);
  } else if (t == "dynamic_lstm") {
    // reference lstm_op.cc; math mirrors ops/rnn_ops.py dynamic_lstm:
    // input [b, t, 4s] pre-projected, gate packing (candidate, in, forget,
    // out), optional peepholes in the bias tail, masked-carry semantics
    // (padded steps output zero and keep the carry)
    Tensor& x = in(op, "Input");
    Tensor& w = in(op, "Weight");
    Tensor& bias = in(op, "Bias");
    int64_t size = w.shape.at(0);
    bool peep = op.attr_bool("use_peepholes", true);
    bool rev = op.attr_bool("is_reverse", false);
    if (op.attr_str("gate_activation", "sigmoid") != "sigmoid" ||
        op.attr_str("cell_activation", "tanh") != "tanh" ||
        op.attr_str("candidate_activation", "tanh") != "tanh")
      throw std::runtime_error(
          "dynamic_lstm: non-default activations unsupported in the "
          "native engine (use the PJRT tier)");
    for (const char* slot : {"H0", "C0"}) {
      auto it = op.inputs.find(slot);
      if (it != op.inputs.end() && !it->second.empty())
        throw std::runtime_error(
            std::string("dynamic_lstm: initial state ") + slot +
            " unsupported in the native engine — the loop always starts "
            "from zero state (use the PJRT tier)");
    }
    if (x.lengths.empty() || x.shape.size() != 3 ||
        x.shape[2] != 4 * size)
      throw std::runtime_error("dynamic_lstm: bad input layout");
    int64_t b = x.shape[0], tt = x.shape[1];
    if (bias.numel() < (peep ? 7 : 4) * size)
      throw std::runtime_error("dynamic_lstm: bias too small (need " +
                               std::to_string((peep ? 7 : 4) * size) +
                               " values)");
    const float* gb = bias.data.data();           // [4s] gate bias
    const float* w_ic = peep ? gb + 4 * size : nullptr;
    const float* w_fc = peep ? gb + 5 * size : nullptr;
    const float* w_oc = peep ? gb + 6 * size : nullptr;
    Tensor hid, cell;
    hid.shape = {b, tt, size};
    cell.shape = {b, tt, size};
    hid.data.assign(b * tt * size, 0.f);
    cell.data.assign(b * tt * size, 0.f);
    hid.lengths = x.lengths;
    cell.lengths = x.lengths;
    std::vector<float> h(size), c(size), gates(4 * size);
    auto sig = [](float v2) { return 1.f / (1.f + std::exp(-v2)); };
    for (int64_t i = 0; i < b; ++i) {
      int64_t len = std::min<int64_t>(x.lengths[i], tt);
      std::fill(h.begin(), h.end(), 0.f);
      std::fill(c.begin(), c.end(), 0.f);
      for (int64_t step = 0; step < len; ++step) {
        int64_t t2 = rev ? len - 1 - step : step;
        const float* xt = x.data.data() + (i * tt + t2) * 4 * size;
        // gates = xt + h @ W + bias   (W [s, 4s])
        for (int64_t g = 0; g < 4 * size; ++g)
          gates[g] = xt[g] + gb[g];
        for (int64_t p = 0; p < size; ++p) {
          float hv = h[p];
          if (hv == 0.f) continue;
          const float* wr = w.data.data() + p * 4 * size;
          for (int64_t g = 0; g < 4 * size; ++g) gates[g] += hv * wr[g];
        }
        float* hp = hid.data.data() + (i * tt + t2) * size;
        float* cp = cell.data.data() + (i * tt + t2) * size;
        for (int64_t j = 0; j < size; ++j) {
          float gc = gates[j];                    // candidate first
          float gi = gates[size + j];
          float gf = gates[2 * size + j];
          float go = gates[3 * size + j];
          if (peep) {
            gi += w_ic[j] * c[j];
            gf += w_fc[j] * c[j];
          }
          float iv = sig(gi), fv = sig(gf);
          float cn = fv * c[j] + iv * std::tanh(gc);
          if (peep) go += w_oc[j] * cn;
          float hn = sig(go) * std::tanh(cn);
          c[j] = cn;
          h[j] = hn;
          hp[j] = hn;
          cp[j] = cn;
        }
      }
    }
    out(op, "Hidden") = std::move(hid);
    if (op.outputs.count("Cell")) out(op, "Cell") = std::move(cell);
  } else if (t == "dynamic_gru") {
    // reference gru_op.cc; math mirrors ops/rnn_ops.py dynamic_gru:
    // input [b, t, 3s] pre-projected; W = [s, 2s | s]; out = (1-u)h + u*c
    Tensor& x = in(op, "Input");
    Tensor& w = in(op, "Weight");
    int64_t size = w.shape.at(0);
    bool rev = op.attr_bool("is_reverse", false);
    if (op.attr_str("gate_activation", "sigmoid") != "sigmoid" ||
        op.attr_str("activation", "tanh") != "tanh")
      throw std::runtime_error(
          "dynamic_gru: non-default activations unsupported in the "
          "native engine (use the PJRT tier)");
    {
      auto it = op.inputs.find("H0");
      if (it != op.inputs.end() && !it->second.empty())
        throw std::runtime_error(
            "dynamic_gru: initial state H0 unsupported in the native "
            "engine — the loop always starts from zero state (use the "
            "PJRT tier)");
    }
    if (x.lengths.empty() || x.shape.size() != 3 ||
        x.shape[2] != 3 * size)
      throw std::runtime_error("dynamic_gru: bad input layout");
    int64_t b = x.shape[0], tt = x.shape[1];
    std::vector<float> zero_bias(3 * size, 0.f);
    if (has_in(op, "Bias") && in(op, "Bias").numel() < 3 * size)
      throw std::runtime_error("dynamic_gru: bias too small");
    const float* gb = has_in(op, "Bias") ? in(op, "Bias").data.data()
                                         : zero_bias.data();
    Tensor hid;
    hid.shape = {b, tt, size};
    hid.data.assign(b * tt * size, 0.f);
    hid.lengths = x.lengths;
    std::vector<float> h(size), ur(2 * size), cvec(size);
    auto sig = [](float v2) { return 1.f / (1.f + std::exp(-v2)); };
    for (int64_t i = 0; i < b; ++i) {
      int64_t len = std::min<int64_t>(x.lengths[i], tt);
      std::fill(h.begin(), h.end(), 0.f);
      for (int64_t step = 0; step < len; ++step) {
        int64_t t2 = rev ? len - 1 - step : step;
        const float* xt = x.data.data() + (i * tt + t2) * 3 * size;
        for (int64_t g = 0; g < 2 * size; ++g) ur[g] = xt[g] + gb[g];
        for (int64_t p = 0; p < size; ++p) {
          float hv = h[p];
          if (hv == 0.f) continue;
          const float* wr = w.data.data() + p * 3 * size;   // [s, 3s]
          for (int64_t g = 0; g < 2 * size; ++g) ur[g] += hv * wr[g];
        }
        for (int64_t g = 0; g < 2 * size; ++g) ur[g] = sig(ur[g]);
        // candidate: x_c + (r*h) @ W_c + b_c
        for (int64_t j = 0; j < size; ++j)
          cvec[j] = xt[2 * size + j] + gb[2 * size + j];
        for (int64_t p = 0; p < size; ++p) {
          float rh = ur[size + p] * h[p];
          if (rh == 0.f) continue;
          const float* wr = w.data.data() + p * 3 * size + 2 * size;
          for (int64_t j = 0; j < size; ++j) cvec[j] += rh * wr[j];
        }
        float* hp = hid.data.data() + (i * tt + t2) * size;
        for (int64_t j = 0; j < size; ++j) {
          float u = ur[j];
          float hn = (1.f - u) * h[j] + u * std::tanh(cvec[j]);
          h[j] = hn;
          hp[j] = hn;
        }
      }
    }
    out(op, "Hidden") = std::move(hid);
  } else if (t == "concat") {
    auto& names = op.inputs.at("X");
    std::vector<const Tensor*> xs;
    for (auto& nm : names) {
      const Tensor* tp = find_tensor(nm);
      if (!tp)
        throw std::runtime_error("concat: input " + nm + " missing");
      xs.push_back(tp);
    }
    int64_t axis = op.attr_int("axis", 0);
    int64_t rank = (int64_t)xs[0]->shape.size();
    if (axis < 0) axis += rank;
    if (axis < 0 || axis >= rank)
      throw std::runtime_error("concat: axis out of range");
    for (auto* xp : xs) {              // non-axis dims must agree: the
      if ((int64_t)xp->shape.size() != rank)   // memcpys below trust it
        throw std::runtime_error("concat: rank mismatch");
      for (int64_t i2 = 0; i2 < rank; ++i2)
        if (i2 != axis && xp->shape[i2] != xs[0]->shape[i2])
          throw std::runtime_error("concat: non-axis dim mismatch");
    }
    Tensor r;
    r.shape = xs[0]->shape;
    int64_t cat = 0;
    for (auto* xp : xs) cat += xp->shape.at(axis);
    r.shape[axis] = cat;
    int64_t outer = 1, inner = 1;
    for (int64_t i2 = 0; i2 < axis; ++i2) outer *= r.shape[i2];
    for (int64_t i2 = axis + 1; i2 < rank; ++i2) inner *= r.shape[i2];
    r.data.resize(outer * cat * inner);
    int64_t off = 0;
    for (auto* xp : xs) {
      int64_t mid = xp->shape.at(axis);
      for (int64_t o = 0; o < outer; ++o)
        std::memcpy(r.data.data() + (o * cat + off) * inner,
                    xp->data.data() + o * mid * inner,
                    mid * inner * 4);
      off += mid;
    }
    r.lengths = xs[0]->lengths;
    out(op) = std::move(r);
  } else if (t == "sum") {
    auto& names = op.inputs.at("X");
    Tensor r;
    for (auto& nm : names) {
      const Tensor* it_t = find_tensor(nm);
      if (!it_t)
        throw std::runtime_error("sum: input " + nm + " missing");
      if (r.data.empty()) {
        r = *it_t;
      } else {
        if (it_t->shape != r.shape)
          throw std::runtime_error("sum: input shape mismatch");
        for (int64_t i2 = 0; i2 < r.numel(); ++i2)
          r.data[i2] += it_t->data[i2];
      }
    }
    out(op) = std::move(r);
  } else {
    throw std::runtime_error(
        "native inference engine: unsupported op '" + t +
        "' (supported: feed/fetch, mul, quantized_mul, elementwise_*, "
        "activations, softmax, scale, reshape, transpose, mean, dropout, "
        "batch_norm, conv2d, quantized_conv2d, pool2d, lookup_table, "
        "sequence_pool, "
        "dynamic_lstm, dynamic_gru, concat, sum — use the PJRT/StableHLO "
        "tier for anything XLA can run)");
  }
  // sequence lengths ride along ops that keep the [batch, time] leading
  // dims (the reference copies lod input->output in these kernels)
  static const char* kSeqTransparent[] = {
      "mul", "quantized_mul", "elementwise_add", "elementwise_sub",
      "elementwise_mul",
      "elementwise_div", "relu", "tanh", "sigmoid", "exp", "sqrt", "abs",
      "softmax", "scale", "dropout"};
  for (auto* st : kSeqTransparent)
    if (t == st) {
      const char* slot = op.inputs.count("X") ? "X" : "Input";
      if (op.inputs.count(slot) && has_in(op, slot)) {
        Tensor& x0 = in(op, slot);
        if (!x0.lengths.empty() && op.outputs.count("Out")) {
          Tensor& o = out(op);
          if (!o.shape.empty() && !x0.shape.empty() &&
              o.shape[0] == x0.shape[0])
            o.lengths = x0.lengths;
        }
      }
      break;
    }
}


void Engine::forward() {
  outputs.clear();
  for (auto& op : block().ops) run_op(op);
  for (auto& n : fetch_names) {
    // both maps: a fetch target may be a loaded parameter passed through
    const Tensor* t = find_tensor(n);
    if (!t)
      throw std::runtime_error("fetch target " + n + " was not produced");
    outputs.push_back(*t);
  }
}

// file provider: (name) -> bytes, plus an existence probe — one
// implementation reads a save_inference_model directory, the other a
// single merged file (the reference's MergeModel.cpp packaging:
// config + params concatenated for one-file deployment)
struct FileProvider {
  std::function<bool(const std::string&)> has;
  std::function<std::string(const std::string&)> get;
};

FileProvider dir_provider(const std::string& dir) {
  return {[dir](const std::string& name) {
            std::ifstream probe(dir + "/" + name);
            return (bool)probe;
          },
          [dir](const std::string& name) {
            return read_file(dir + "/" + name);
          }};
}

// merged container: "PTPUMRG1" u64 n, then per entry
// [u32 name_len][name][u64 data_len][data] — entry bytes are the exact
// on-disk file bytes (tensor entries keep their CRC framing)
FileProvider merged_provider(const std::string& path) {
  // the blob is held once; the index stores (offset, length) views into
  // it, so peak memory matches the directory path (one transient copy
  // per entry at parse time, nothing else)
  auto blob = std::make_shared<const std::string>(read_file(path));
  auto index = std::make_shared<
      std::map<std::string, std::pair<size_t, size_t>>>();
  static const char kMagic[] = "PTPUMRG1";
  if (blob->size() < 16 || std::memcmp(blob->data(), kMagic, 8) != 0)
    throw std::runtime_error(path + ": not a merged ptpu model");
  size_t off = 8;
  // overflow-safe: off <= size always holds, so compare against the
  // REMAINING bytes (off + n could wrap for a crafted 64-bit length)
  auto need = [&](uint64_t n) {
    if (n > blob->size() - off)
      throw std::runtime_error(path + ": truncated merged model");
  };
  need(8);
  uint64_t n_entries;
  std::memcpy(&n_entries, blob->data() + off, 8);
  off += 8;
  for (uint64_t i = 0; i < n_entries; ++i) {
    need(4);
    uint32_t nlen;
    std::memcpy(&nlen, blob->data() + off, 4);
    off += 4;
    need(nlen);
    std::string name = blob->substr(off, nlen);
    off += nlen;
    need(8);
    uint64_t dlen;
    std::memcpy(&dlen, blob->data() + off, 8);
    off += 8;
    need(dlen);
    (*index)[name] = {off, (size_t)dlen};
    off += dlen;
  }
  return {[index](const std::string& name) {
            return index->count(name) > 0;
          },
          [blob, index](const std::string& name) {
            auto it = index->find(name);
            if (it == index->end())
              throw std::runtime_error("merged model: no entry " + name);
            return blob->substr(it->second.first, it->second.second);
          }};
}

Engine* load_engine_from(const FileProvider& files) {
  auto eng = std::make_unique<Engine>();
  // __model__ is the raw canonical-JSON desc (desc.py serialize_to_string);
  // only the tensor files carry the CRC framing
  eng->prog = std::make_shared<const ProgramDesc>(
      parse_program(files.get("__model__")));
  const BlockDesc& b = eng->prog->blocks.at(0);
  // order by the ops' 'col' attr, NOT block order: save_inference_model
  // prepends feed ops one at a time, so block order is the REVERSE of
  // the feeded_var_names/column order the ABI documents
  std::vector<std::pair<int64_t, std::string>> feeds, fetches;
  for (auto& op : b.ops) {
    if (op.type == "feed")
      feeds.emplace_back(op.attr_int("col", (int64_t)feeds.size()),
                         op.inputs.at("X").at(0));
    if (op.type == "fetch")
      fetches.emplace_back(op.attr_int("col", (int64_t)fetches.size()),
                           op.inputs.at("X").at(0));
  }
  std::sort(feeds.begin(), feeds.end());
  std::sort(fetches.begin(), fetches.end());
  for (auto& p : feeds) eng->feed_names.push_back(p.second);
  for (auto& p : fetches) eng->fetch_names.push_back(p.second);
  auto params = std::make_shared<std::map<std::string, Tensor>>();
  for (auto& kv : b.vars) {
    if (!kv.second.persistable) continue;
    if (!files.has(kv.first)) continue;  // e.g. feed/fetch holder vars
    (*params)[kv.first] =
        parse_tensor(unframe(files.get(kv.first), kv.first), kv.first);
  }
  eng->params = std::move(params);
  return eng.release();
}

Engine* load_engine(const std::string& dir) {
  return load_engine_from(dir_provider(dir));
}

thread_local std::string g_err;

}  // namespace
}  // namespace ptpu

// ---------------------------------------------------------------------------
// C ABI — shape mirrors reference capi/gradient_machine.h
// ---------------------------------------------------------------------------

extern "C" {

const char* ptpu_last_error() { return ptpu::g_err.c_str(); }

void* ptpu_create_for_inference(const char* model_dir) {
  try {
    return ptpu::load_engine(model_dir);
  } catch (const std::exception& e) {
    ptpu::g_err = e.what();
    return nullptr;
  }
}

// single-file deployment — the analog of the reference's merged model
// (trainer/MergeModel.cpp packs ModelConfig + params for capi)
void* ptpu_create_for_inference_merged(const char* model_file) {
  try {
    return ptpu::load_engine_from(ptpu::merged_provider(model_file));
  } catch (const std::exception& e) {
    ptpu::g_err = e.what();
    return nullptr;
  }
}

int ptpu_num_inputs(void* h) {
  return (int)((ptpu::Engine*)h)->feed_names.size();
}
const char* ptpu_input_name(void* h, int i) {
  return ((ptpu::Engine*)h)->feed_names.at(i).c_str();
}
int ptpu_num_outputs(void* h) {
  return (int)((ptpu::Engine*)h)->fetch_names.size();
}
const char* ptpu_output_name(void* h, int i) {
  return ((ptpu::Engine*)h)->fetch_names.at(i).c_str();
}

// inputs follow the feed-op column order (ptpu_input_name order).
// `lengths` (nullable, per input) carries sequence row lengths — the
// reference capi's paddle_arguments_set_sequence_start_positions
// (capi/arguments.cpp), dense-pair form: padded data + int32 lengths.
int ptpu_forward_seq(void* h, const float* const* inputs,
                     const int64_t* const* shapes, const int* ndims,
                     const int32_t* const* lengths, int n_inputs) {
  auto* eng = (ptpu::Engine*)h;
  try {
    if (n_inputs != (int)eng->feed_names.size())
      throw std::runtime_error("expected " +
                               std::to_string(eng->feed_names.size()) +
                               " inputs");
    for (int i = 0; i < n_inputs; ++i) {
      ptpu::Tensor t;
      int64_t n = 1;
      for (int d = 0; d < ndims[i]; ++d) {
        t.shape.push_back(shapes[i][d]);
        n *= shapes[i][d];
      }
      t.data.assign(inputs[i], inputs[i] + n);
      if (lengths && lengths[i])
        t.lengths.assign(lengths[i], lengths[i] + t.shape.at(0));
      eng->vars[eng->feed_names[i]] = std::move(t);
    }
    eng->forward();
    return 0;
  } catch (const std::exception& e) {
    ptpu::g_err = e.what();
    return 1;
  }
}

int ptpu_forward(void* h, const float* const* inputs,
                 const int64_t* const* shapes, const int* ndims,
                 int n_inputs) {
  return ptpu_forward_seq(h, inputs, shapes, ndims, nullptr, n_inputs);
}

int ptpu_output_rank(void* h, int i) {
  return (int)((ptpu::Engine*)h)->outputs.at(i).shape.size();
}
const int64_t* ptpu_output_shape(void* h, int i) {
  return ((ptpu::Engine*)h)->outputs.at(i).shape.data();
}
const float* ptpu_output_data(void* h, int i) {
  return ((ptpu::Engine*)h)->outputs.at(i).data.data();
}
// non-null when output i is a sequence (one int32 length per batch row)
const int32_t* ptpu_output_lengths(void* h, int i) {
  auto& t = ((ptpu::Engine*)h)->outputs.at(i);
  return t.lengths.empty() ? nullptr : t.lengths.data();
}

void ptpu_destroy(void* h) { delete (ptpu::Engine*)h; }

// Shared-parameter clone — the analog of the reference's
// paddle_gradient_machine_create_shared_param + the multi_thread example
// (capi/examples/model_inference/multi_thread/main.c): the clone shares
// the immutable desc and loaded weights with `h` and owns only its
// activation map, so each serving thread forwards on its own clone with
// no synchronization and ~zero extra memory.  Destroy each clone with
// ptpu_destroy; the weights free when the last holder goes.
void* ptpu_clone_shared(void* h) {
  try {
    auto* src = (ptpu::Engine*)h;
    auto* eng = new ptpu::Engine();
    eng->prog = src->prog;
    eng->params = src->params;
    eng->feed_names = src->feed_names;
    eng->fetch_names = src->fetch_names;
    return eng;
  } catch (const std::exception& e) {
    ptpu::g_err = e.what();
    return nullptr;
  }
}

}  // extern "C"
