// Shared native ProgramDesc model + JSON wire parsing — the header both
// native TUs (ir.cc: validation/scheduling/liveness; capi.cc: the C
// inference ABI) build on.  Counterpart of the reference's desc headers
// (paddle/framework/program_desc.h, block_desc.h, op_desc.h, var_desc.h);
// the wire format is the canonical JSON of fluid/core/desc.py.
#pragma once

#include <cstdint>
#include <map>
#include <string>
#include <vector>

#include "json.h"

namespace ptpu {

struct VarDesc {
  std::string name, type, dtype;
  std::vector<int64_t> shape;
  bool has_shape = false;
  bool persistable = false;
};

struct OpDesc {
  std::string type;
  // slot -> ordered var names
  std::map<std::string, std::vector<std::string>> inputs, outputs;
  JsonPtr attrs;  // opaque; block refs = {"__block__": idx}

  std::vector<std::string> all_inputs() const {
    std::vector<std::string> v;
    for (auto& kv : inputs) v.insert(v.end(), kv.second.begin(),
                                     kv.second.end());
    return v;
  }
  std::vector<std::string> all_outputs() const {
    std::vector<std::string> v;
    for (auto& kv : outputs) v.insert(v.end(), kv.second.begin(),
                                      kv.second.end());
    return v;
  }
  std::vector<int> block_attrs() const {
    std::vector<int> out;
    if (attrs && attrs->type == Json::OBJECT) {
      for (auto& kv : attrs->obj) {
        if (kv.second->type == Json::OBJECT) {
          auto b = kv.second->get("__block__");
          if (b && b->type == Json::INT) out.push_back((int)b->i);
        }
      }
    }
    return out;
  }

  // attr conveniences for kernel code (capi.cc)
  int64_t attr_int(const std::string& k, int64_t dflt) const {
    if (!attrs || attrs->type != Json::OBJECT) return dflt;
    auto a = attrs->get(k);
    return (a && a->type == Json::INT) ? a->i : dflt;
  }
  double attr_num(const std::string& k, double dflt) const {
    if (!attrs || attrs->type != Json::OBJECT) return dflt;
    auto a = attrs->get(k);
    if (a && a->type == Json::DOUBLE) return a->d;
    if (a && a->type == Json::INT) return (double)a->i;
    return dflt;
  }
  bool attr_bool(const std::string& k, bool dflt) const {
    if (!attrs || attrs->type != Json::OBJECT) return dflt;
    auto a = attrs->get(k);
    return (a && a->type == Json::BOOL) ? a->b : dflt;
  }
  std::string attr_str(const std::string& k, const std::string& dflt) const {
    if (!attrs || attrs->type != Json::OBJECT) return dflt;
    auto a = attrs->get(k);
    return (a && a->type == Json::STRING) ? a->s : dflt;
  }
  std::vector<int64_t> attr_ints(const std::string& k) const {
    std::vector<int64_t> out;
    if (!attrs || attrs->type != Json::OBJECT) return out;
    auto a = attrs->get(k);
    if (a && a->type == Json::ARRAY)
      for (auto& e : a->arr)
        if (e->type == Json::INT) out.push_back(e->i);
    return out;
  }
};

struct BlockDesc {
  int idx = 0, parent_idx = -1;
  std::map<std::string, VarDesc> vars;
  std::vector<OpDesc> ops;
};

struct ProgramDesc {
  int version = 1;
  std::vector<BlockDesc> blocks;
};

// defined in ir.cc (one definition; capi.cc links against it)
ProgramDesc parse_program(const std::string& text);
std::string reserialize(const std::string& text);

}  // namespace ptpu
