// PJRT-tier native serving: load an exported StableHLO inference module
// through any PJRT C-API plugin (.so exporting GetPjrtApi) and execute it
// on that plugin's device — TPU serving with no Python in the process.
//
// This is the TPU-native analog of the reference's C++ inference path
// (paddle/inference/io.h:32 Load + Executor::Run) and closes the loop on
// SURVEY §7 step 2's "PJRT C API where native code is required": the
// device/memory layer the reference implements with platform/ +
// memory/buddy_allocator is the PJRT client here — buffers, transfers,
// compilation, execution, all through the stable C ABI.
//
// Inputs: <model_dir>/model.stablehlo (textual MLIR emitted by
// fluid.io.save_inference_model(..., export_stablehlo=True)) and
// model.stablehlo.json ({"inputs": [{name, shape, dtype, lod?}],
// "params": [{name, shape, dtype}], "outputs": [{shape, dtype}]}).
// Parameters are module ARGUMENTS: each is loaded from the CRC-framed
// tensor file <model_dir>/<name> (the save_persistables artifact) and
// uploaded to the device ONCE at create time — so the module text stays
// small at any model size and re-export is not needed per checkpoint.
// Feeds are dtype-tagged (float32/int32/int64); sequence feeds appear as
// a data input plus an int32 "<name>.lengths" input.

#include <dlfcn.h>

#include <cstring>
#include <fstream>
#include <memory>
#include <sstream>
#include <string>
#include <vector>

#include "json.h"
#include "tensor_file.h"
#include "xla/pjrt/c/pjrt_c_api.h"

namespace ptpu_pjrt {
namespace {

thread_local std::string g_err;

using ptpu::read_file;

PJRT_Buffer_Type dtype_to_pjrt(const std::string& dt) {
  if (dt == "float32") return PJRT_Buffer_Type_F32;
  if (dt == "int32") return PJRT_Buffer_Type_S32;
  if (dt == "int64") return PJRT_Buffer_Type_S64;
  if (dt == "bfloat16") return PJRT_Buffer_Type_BF16;
  if (dt == "float64") return PJRT_Buffer_Type_F64;
  if (dt == "float16") return PJRT_Buffer_Type_F16;
  if (dt == "int8") return PJRT_Buffer_Type_S8;
  if (dt == "uint8") return PJRT_Buffer_Type_U8;
  if (dt == "bool") return PJRT_Buffer_Type_PRED;
  throw std::runtime_error("unsupported dtype " + dt);
}

struct IoSpec {
  std::string name;
  std::string file;    // tensor file (params only; defaults to name)
  std::vector<int64_t> shape;
  std::string dtype;
};

struct Meta {
  std::vector<IoSpec> inputs;
  std::vector<IoSpec> params;
  std::vector<IoSpec> outputs;
};

void parse_iospec(const ptpu::JsonPtr& e, IoSpec* s, bool named) {
  if (named) s->name = e->at("name")->s;
  s->file = e->get("file") ? e->at("file")->s : s->name;
  s->dtype = e->get("dtype") ? e->at("dtype")->s : "float32";
  if (e->get("shape"))
    for (auto& d : e->at("shape")->arr) s->shape.push_back(d->i);
}

Meta parse_meta(const std::string& text) {
  ptpu::JsonParser p(text);
  auto root = p.parse();
  Meta m;
  for (auto& e : root->at("inputs")->arr) {
    IoSpec s;
    parse_iospec(e, &s, true);
    m.inputs.push_back(std::move(s));
  }
  if (root->get("params"))
    for (auto& e : root->at("params")->arr) {
      IoSpec s;
      parse_iospec(e, &s, true);
      m.params.push_back(std::move(s));
    }
  for (auto& e : root->at("outputs")->arr) {
    IoSpec s;
    parse_iospec(e, &s, false);
    m.outputs.push_back(std::move(s));
  }
  return m;
}

struct Runner {
  void* dl = nullptr;
  const PJRT_Api* api = nullptr;
  PJRT_Client* client = nullptr;
  PJRT_Device* device = nullptr;
  PJRT_LoadedExecutable* exec = nullptr;
  Meta meta;
  std::vector<PJRT_Buffer*> param_bufs;   // device-resident, upload once
  // last forward's outputs, copied to host (raw bytes, meta dtype)
  std::vector<std::vector<int64_t>> out_shapes;
  std::vector<std::string> out_dtypes;
  std::vector<std::vector<char>> out_raw;
  bool out_dtypes_verified = false;  // element-type check latched once

  ~Runner();
  void check(PJRT_Error* err, const char* what);
  void load(const std::string& model_dir, const std::string& plugin);
  PJRT_Buffer* upload(const void* data, const std::string& dtype,
                      const std::vector<int64_t>& dims, const char* what);
  void forward(const void* const* inputs);
  void await_event(PJRT_Event* ev, const char* what);
  void destroy_buffer(PJRT_Buffer* b);
};

// RAII: every PJRT buffer created during forward() is destroyed even when
// a check() throws mid-flight — a serving loop that retries on error must
// not leak device HBM
struct BufferGuard {
  Runner* r;
  std::vector<PJRT_Buffer*>* bufs;
  ~BufferGuard() {
    for (auto* b : *bufs)
      if (b) r->destroy_buffer(b);
  }
};

void Runner::await_event(PJRT_Event* ev, const char* what) {
  if (!ev) return;
  PJRT_Event_Await_Args aw;
  std::memset(&aw, 0, sizeof(aw));
  aw.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
  aw.event = ev;
  PJRT_Error* err = api->PJRT_Event_Await(&aw);
  PJRT_Event_Destroy_Args ed;
  std::memset(&ed, 0, sizeof(ed));
  ed.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
  ed.event = ev;
  api->PJRT_Event_Destroy(&ed);
  check(err, what);
}

void Runner::destroy_buffer(PJRT_Buffer* b) {
  PJRT_Buffer_Destroy_Args bd;
  std::memset(&bd, 0, sizeof(bd));
  bd.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
  bd.buffer = b;
  api->PJRT_Buffer_Destroy(&bd);
}

void Runner::check(PJRT_Error* err, const char* what) {
  if (!err) return;
  PJRT_Error_Message_Args m;
  std::memset(&m, 0, sizeof(m));
  m.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
  m.error = err;
  api->PJRT_Error_Message(&m);
  std::string msg = std::string(what) + ": " +
                    std::string(m.message, m.message_size);
  PJRT_Error_Destroy_Args d;
  std::memset(&d, 0, sizeof(d));
  d.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  d.error = err;
  api->PJRT_Error_Destroy(&d);
  throw std::runtime_error(msg);
}

void Runner::load(const std::string& model_dir, const std::string& plugin) {
  meta = parse_meta(read_file(model_dir + "/model.stablehlo.json"));
  std::string code = read_file(model_dir + "/model.stablehlo");

  dl = dlopen(plugin.c_str(), RTLD_NOW | RTLD_LOCAL);
  if (!dl) throw std::runtime_error(std::string("dlopen: ") + dlerror());
  auto get_api = (const PJRT_Api* (*)())dlsym(dl, "GetPjrtApi");
  if (!get_api) throw std::runtime_error("plugin has no GetPjrtApi");
  api = get_api();

  PJRT_Plugin_Initialize_Args pi;
  std::memset(&pi, 0, sizeof(pi));
  pi.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
  check(api->PJRT_Plugin_Initialize(&pi), "plugin init");

  // plugin-specific client options: standard libtpu/CPU plugins need
  // none; bespoke plugins (e.g. proxy/tunnel backends) read NamedValues.
  // Sourced from $PTPU_PJRT_CREATE_OPTIONS (JSON object of str|int),
  // mirroring how jax passes plugin options at register time.
  std::vector<PJRT_NamedValue> nvs;
  std::vector<std::string> nv_keys, nv_strs;  // stable storage
  std::vector<int64_t> nv_ints;
  ptpu::JsonPtr opt_root;
  const char* opt_env = getenv("PTPU_PJRT_CREATE_OPTIONS");
  std::string opt_text = opt_env ? opt_env : "";
  if (!opt_text.empty()) {
    ptpu::JsonParser op(opt_text);
    opt_root = op.parse();
    nv_keys.reserve(opt_root->obj.size());
    nv_strs.reserve(opt_root->obj.size());
    nv_ints.reserve(opt_root->obj.size());
    for (auto& kv : opt_root->obj) {
      nv_keys.push_back(kv.first);
      PJRT_NamedValue nv;
      std::memset(&nv, 0, sizeof(nv));
      nv.struct_size = PJRT_NamedValue_STRUCT_SIZE;
      nv.name = nv_keys.back().c_str();
      nv.name_size = nv_keys.back().size();
      if (kv.second->type == ptpu::Json::STRING) {
        nv_strs.push_back(kv.second->s);
        nv.type = PJRT_NamedValue_kString;
        nv.string_value = nv_strs.back().c_str();
        nv.value_size = nv_strs.back().size();
      } else if (kv.second->type == ptpu::Json::INT) {
        nv_ints.push_back(kv.second->i);
        nv.type = PJRT_NamedValue_kInt64;
        nv.int64_value = nv_ints.back();
        nv.value_size = 1;
      } else {
        throw std::runtime_error("create option " + kv.first +
                                 ": only string/int supported");
      }
      nvs.push_back(nv);
    }
  }

  PJRT_Client_Create_Args cc;
  std::memset(&cc, 0, sizeof(cc));
  cc.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  cc.create_options = nvs.empty() ? nullptr : nvs.data();
  cc.num_options = nvs.size();
  check(api->PJRT_Client_Create(&cc), "client create");
  client = cc.client;

  PJRT_Client_AddressableDevices_Args ad;
  std::memset(&ad, 0, sizeof(ad));
  ad.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
  ad.client = client;
  check(api->PJRT_Client_AddressableDevices(&ad), "devices");
  if (ad.num_addressable_devices == 0)
    throw std::runtime_error("no addressable devices");
  device = ad.addressable_devices[0];

  PJRT_Program prog;
  std::memset(&prog, 0, sizeof(prog));
  prog.struct_size = PJRT_Program_STRUCT_SIZE;
  prog.code = code.data();
  prog.code_size = code.size();
  static const char kFmt[] = "mlir";
  prog.format = kFmt;
  prog.format_size = sizeof(kFmt) - 1;

  // hand-encoded CompileOptionsProto: executable_build_options(field 3) {
  //   num_replicas(4)=1, num_partitions(5)=1 }
  static const char kOpts[] = {0x1a, 0x04, 0x20, 0x01, 0x28, 0x01};

  PJRT_Client_Compile_Args co;
  std::memset(&co, 0, sizeof(co));
  co.struct_size = PJRT_Client_Compile_Args_STRUCT_SIZE;
  co.client = client;
  co.program = &prog;
  co.compile_options = kOpts;
  co.compile_options_size = sizeof(kOpts);
  check(api->PJRT_Client_Compile(&co), "compile");
  exec = co.executable;

  // trust the compiled executable, not the json, for the output count —
  // a stale/hand-edited meta undercounting outputs would otherwise make
  // Execute write output buffer pointers past the end of out_bufs
  PJRT_LoadedExecutable_GetExecutable_Args ge;
  std::memset(&ge, 0, sizeof(ge));
  ge.struct_size = PJRT_LoadedExecutable_GetExecutable_Args_STRUCT_SIZE;
  ge.loaded_executable = exec;
  check(api->PJRT_LoadedExecutable_GetExecutable(&ge), "get executable");
  PJRT_Executable_NumOutputs_Args no;
  std::memset(&no, 0, sizeof(no));
  no.struct_size = PJRT_Executable_NumOutputs_Args_STRUCT_SIZE;
  no.executable = ge.executable;
  PJRT_Error* no_err = api->PJRT_Executable_NumOutputs(&no);
  {
    // the queried executable is caller-owned — release it before any throw
    PJRT_Executable_Destroy_Args ed;
    std::memset(&ed, 0, sizeof(ed));
    ed.struct_size = PJRT_Executable_Destroy_Args_STRUCT_SIZE;
    ed.executable = ge.executable;
    api->PJRT_Executable_Destroy(&ed);
  }
  check(no_err, "num outputs");
  if (no.num_outputs != meta.outputs.size())
    throw std::runtime_error(
        "model.stablehlo.json outputs (" +
        std::to_string(meta.outputs.size()) +
        ") disagree with compiled executable (" +
        std::to_string(no.num_outputs) + ") — stale meta?");

  // parameters: read each CRC-framed tensor file, upload once.  A dtype
  // mismatch between file and meta is a stale-export error, not a cast.
  param_bufs.reserve(meta.params.size());
  for (auto& p : meta.params) {
    ptpu::RawTensor t = ptpu::parse_tensor_raw(
        ptpu::unframe(read_file(model_dir + "/" + p.file), p.name), p.name);
    if (t.dtype != p.dtype)
      throw std::runtime_error(
          "param " + p.name + ": file dtype " + t.dtype +
          " != meta dtype " + p.dtype + " (stale export?)");
    if (t.shape != p.shape)
      throw std::runtime_error("param " + p.name +
                               ": file/meta shape mismatch");
    param_bufs.push_back(
        upload(t.data.data(), p.dtype, p.shape, p.name.c_str()));
  }
}

PJRT_Buffer* Runner::upload(const void* data, const std::string& dtype,
                            const std::vector<int64_t>& dims,
                            const char* what) {
  PJRT_Client_BufferFromHostBuffer_Args hb;
  std::memset(&hb, 0, sizeof(hb));
  hb.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
  hb.client = client;
  hb.data = data;
  hb.type = dtype_to_pjrt(dtype);
  hb.dims = dims.data();
  hb.num_dims = dims.size();
  hb.host_buffer_semantics =
      PJRT_HostBufferSemantics_kImmutableUntilTransferCompletes;
  hb.device = device;
  check(api->PJRT_Client_BufferFromHostBuffer(&hb), what);
  await_event(hb.done_with_host_buffer, what);
  return hb.buffer;
}

void Runner::forward(const void* const* inputs) {
  size_t n = meta.inputs.size();
  std::vector<PJRT_Buffer*> in_bufs(n, nullptr);
  size_t n_out = meta.outputs.size();
  std::vector<PJRT_Buffer*> out_bufs(n_out, nullptr);
  BufferGuard in_guard{this, &in_bufs};
  BufferGuard out_guard{this, &out_bufs};

  for (size_t i = 0; i < n; ++i)
    in_bufs[i] = upload(inputs[i], meta.inputs[i].dtype,
                        meta.inputs[i].shape, "h2d");
  // argument order matches the exported function: params then feeds
  std::vector<PJRT_Buffer*> args(param_bufs);
  args.insert(args.end(), in_bufs.begin(), in_bufs.end());
  PJRT_Buffer* const* arg_list = args.data();
  PJRT_Buffer** out_list = out_bufs.data();
  PJRT_Event* done = nullptr;

  PJRT_ExecuteOptions opts;
  std::memset(&opts, 0, sizeof(opts));
  opts.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;

  PJRT_LoadedExecutable_Execute_Args ex;
  std::memset(&ex, 0, sizeof(ex));
  ex.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
  ex.executable = exec;
  ex.options = &opts;
  ex.argument_lists = &arg_list;
  ex.num_devices = 1;
  ex.num_args = args.size();
  ex.output_lists = &out_list;
  ex.device_complete_events = &done;
  check(api->PJRT_LoadedExecutable_Execute(&ex), "execute");
  await_event(done, "execute await");

  out_shapes.assign(n_out, {});
  out_dtypes.assign(n_out, "");
  out_raw.assign(n_out, {});
  for (size_t i = 0; i < n_out; ++i) {
    PJRT_Buffer_Dimensions_Args dm;
    std::memset(&dm, 0, sizeof(dm));
    dm.struct_size = PJRT_Buffer_Dimensions_Args_STRUCT_SIZE;
    dm.buffer = out_bufs[i];
    check(api->PJRT_Buffer_Dimensions(&dm), "dims");
    out_shapes[i].assign(dm.dims, dm.dims + dm.num_dims);
    int64_t numel = 1;
    for (auto d : out_shapes[i]) numel *= d;
    out_dtypes[i] = meta.outputs[i].dtype;
    // Never trust the meta dtype for the d2h byte width: a stale or
    // hand-edited model.stablehlo.json would silently reinterpret the
    // bytes.  Verify against the executable's actual element type —
    // invariant for a compiled executable, so latched after the first
    // forward rather than paid per call.
    if (!out_dtypes_verified) {
      PJRT_Buffer_ElementType_Args et;
      std::memset(&et, 0, sizeof(et));
      et.struct_size = PJRT_Buffer_ElementType_Args_STRUCT_SIZE;
      et.buffer = out_bufs[i];
      check(api->PJRT_Buffer_ElementType(&et), "element_type");
      bool mismatch;
      try {
        mismatch = et.type != dtype_to_pjrt(out_dtypes[i]);
      } catch (const std::exception&) {
        mismatch = true;  // meta dtype not even mappable
      }
      if (mismatch)
        throw std::runtime_error(
            "output " + std::to_string(i) + ": meta dtype '" +
            out_dtypes[i] + "' does not match the compiled buffer's "
            "element type (" + std::to_string((int)et.type) +
            ") — regenerate model.stablehlo.json");
    }
    int64_t w = ptpu::dtype_width(out_dtypes[i]);
    out_raw[i].resize(numel * w);

    PJRT_Buffer_ToHostBuffer_Args th;
    std::memset(&th, 0, sizeof(th));
    th.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
    th.src = out_bufs[i];
    th.dst = out_raw[i].data();
    th.dst_size = out_raw[i].size();
    check(api->PJRT_Buffer_ToHostBuffer(&th), "d2h");
    await_event(th.event, "d2h await");
  }
  out_dtypes_verified = true;
  // in/out buffers are destroyed by the BufferGuards (also on throw)
}

Runner::~Runner() {
  if (api)
    for (auto* b : param_bufs)
      if (b) destroy_buffer(b);
  if (api && exec) {
    PJRT_LoadedExecutable_Destroy_Args a;
    std::memset(&a, 0, sizeof(a));
    a.struct_size = PJRT_LoadedExecutable_Destroy_Args_STRUCT_SIZE;
    a.executable = exec;
    api->PJRT_LoadedExecutable_Destroy(&a);
  }
  if (api && client) {
    PJRT_Client_Destroy_Args a;
    std::memset(&a, 0, sizeof(a));
    a.struct_size = PJRT_Client_Destroy_Args_STRUCT_SIZE;
    a.client = client;
    api->PJRT_Client_Destroy(&a);
  }
  // the plugin .so stays loaded (unloading PJRT plugins is not safe)
}

}  // namespace
}  // namespace ptpu_pjrt

extern "C" {

const char* ptpu_pjrt_last_error() { return ptpu_pjrt::g_err.c_str(); }

void* ptpu_pjrt_create(const char* model_dir, const char* plugin_path) {
  auto r = std::make_unique<ptpu_pjrt::Runner>();
  try {
    r->load(model_dir, plugin_path);
    return r.release();
  } catch (const std::exception& e) {
    ptpu_pjrt::g_err = e.what();
    return nullptr;
  }
}

int ptpu_pjrt_num_inputs(void* h) {
  return (int)((ptpu_pjrt::Runner*)h)->meta.inputs.size();
}
const char* ptpu_pjrt_input_name(void* h, int i) {
  return ((ptpu_pjrt::Runner*)h)->meta.inputs.at(i).name.c_str();
}
const char* ptpu_pjrt_input_dtype(void* h, int i) {
  return ((ptpu_pjrt::Runner*)h)->meta.inputs.at(i).dtype.c_str();
}
int ptpu_pjrt_num_outputs(void* h) {
  return (int)((ptpu_pjrt::Runner*)h)->meta.outputs.size();
}

// dtype-tagged forward: inputs[i] points at data of
// ptpu_pjrt_input_dtype(h, i), in model.stablehlo.json order; shapes are
// fixed at export time
int ptpu_pjrt_forward_ex(void* h, const void* const* inputs) {
  try {
    ((ptpu_pjrt::Runner*)h)->forward(inputs);
    return 0;
  } catch (const std::exception& e) {
    ptpu_pjrt::g_err = e.what();
    return 1;
  }
}

// legacy float32-only entry: valid only when every input is float32
int ptpu_pjrt_forward(void* h, const float* const* inputs) {
  auto* r = (ptpu_pjrt::Runner*)h;
  for (auto& s : r->meta.inputs)
    if (s.dtype != "float32") {
      ptpu_pjrt::g_err = "input " + s.name + " is " + s.dtype +
                         ": use ptpu_pjrt_forward_ex";
      return 1;
    }
  return ptpu_pjrt_forward_ex(h, (const void* const*)inputs);
}

int ptpu_pjrt_output_rank(void* h, int i) {
  return (int)((ptpu_pjrt::Runner*)h)->out_shapes.at(i).size();
}
const int64_t* ptpu_pjrt_output_shape(void* h, int i) {
  return ((ptpu_pjrt::Runner*)h)->out_shapes.at(i).data();
}
const char* ptpu_pjrt_output_dtype(void* h, int i) {
  return ((ptpu_pjrt::Runner*)h)->out_dtypes.at(i).c_str();
}
const void* ptpu_pjrt_output_bytes(void* h, int i) {
  return ((ptpu_pjrt::Runner*)h)->out_raw.at(i).data();
}
// float32 view of output i (null + error when the output is not f32)
const float* ptpu_pjrt_output_data(void* h, int i) {
  auto* r = (ptpu_pjrt::Runner*)h;
  if (r->out_dtypes.at(i) != "float32") {
    ptpu_pjrt::g_err = "output " + std::to_string(i) + " is " +
                       r->out_dtypes.at(i) + ": use ptpu_pjrt_output_bytes";
    return nullptr;
  }
  return (const float*)r->out_raw.at(i).data();
}

void ptpu_pjrt_destroy(void* h) { delete (ptpu_pjrt::Runner*)h; }

}  // extern "C"
