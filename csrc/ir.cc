// Graph IR: native Program/Block/Op/Var model + validation + scheduling +
// liveness-based memory planning.
//
// TPU-native counterpart of the reference's C++ desc layer
// (paddle/framework/program_desc.cc, block_desc.cc, op_desc.cc,
// var_desc.cc), its executor's per-block walk (executor.cc:77), and the
// Python memory_optimization_transpiler's ControlFlowGraph liveness pass
// (python/paddle/v2/fluid/memory_optimization_transpiler.py:33,90) — here a
// native analysis the Python side calls through ctypes.  Where the reference
// executor *runs* ops in block order, the TPU executor compiles whole blocks
// with XLA; what remains native is what must be fast and host-side: parsing,
// validation, topological scheduling, liveness/reuse planning.

#include <algorithm>
#include <queue>
#include <set>
#include <unordered_map>
#include <unordered_set>

#include "desc.h"

namespace ptpu {

// ---------------------------------------------------------------------------
// parse / serialize (canonical JSON wire format shared with desc.py)
// ---------------------------------------------------------------------------

static VarDesc parse_var(const JsonPtr& j) {
  VarDesc v;
  v.name = j->at("name")->s;
  v.type = j->at("type")->s;
  v.dtype = j->at("dtype")->s;
  auto sh = j->get("shape");
  if (sh && sh->type == Json::ARRAY) {
    v.has_shape = true;
    for (auto& e : sh->arr) v.shape.push_back(e->i);
  }
  auto p = j->get("persistable");
  v.persistable = p && p->type == Json::BOOL && p->b;
  return v;
}

static OpDesc parse_op(const JsonPtr& j) {
  OpDesc op;
  op.type = j->at("type")->s;
  auto ins = j->get("inputs");
  if (ins)
    for (auto& kv : ins->obj) {
      auto& lst = op.inputs[kv.first];
      for (auto& e : kv.second->arr) lst.push_back(e->s);
    }
  auto outs = j->get("outputs");
  if (outs)
    for (auto& kv : outs->obj) {
      auto& lst = op.outputs[kv.first];
      for (auto& e : kv.second->arr) lst.push_back(e->s);
    }
  op.attrs = j->get("attrs");
  return op;
}

ProgramDesc parse_program(const std::string& text) {
  JsonParser p(text);
  JsonPtr root = p.parse();
  ProgramDesc prog;
  prog.blocks.clear();
  auto ver = root->get("version");
  if (ver && ver->type == Json::INT) prog.version = (int)ver->i;
  for (auto& bj : root->at("blocks")->arr) {
    BlockDesc b;
    b.idx = (int)bj->at("idx")->i;
    auto pi = bj->get("parent_idx");
    b.parent_idx = pi ? (int)pi->i : -1;
    auto vars = bj->get("vars");
    if (vars)
      for (auto& kv : vars->obj) b.vars[kv.first] = parse_var(kv.second);
    auto ops = bj->get("ops");
    if (ops)
      for (auto& oj : ops->arr) b.ops.push_back(parse_op(oj));
    prog.blocks.push_back(std::move(b));
  }
  return prog;
}

// rebuild the Json tree from the parsed model and write canonically; note
// vars' full field set must survive, so we keep the original var/op attr
// subtrees when round-tripping.  For byte-exact round trips we simply
// re-serialize the *parsed JSON tree* (not the typed model).
std::string reserialize(const std::string& text) {
  JsonParser p(text);
  JsonPtr root = p.parse();
  std::string out;
  write_json(root, &out);
  return out;
}

// ---------------------------------------------------------------------------
// validation — the analog of the reference's OpDesc::CheckAttrs/InferShape
// pre-flight and executor var-existence checks (executor.cc:36-75)
// ---------------------------------------------------------------------------

std::vector<std::string> validate_program(const ProgramDesc& prog) {
  std::vector<std::string> errors;
  int nblocks = (int)prog.blocks.size();
  if (nblocks == 0) {
    errors.push_back("program has no blocks");
    return errors;
  }
  for (auto& b : prog.blocks) {
    // parent must come earlier (blocks are created parent-first); this
    // also rules out parent cycles, so the visible() walk terminates
    bool parent_ok = b.parent_idx < b.idx;
    if (b.parent_idx >= nblocks || !parent_ok)
      errors.push_back("block " + std::to_string(b.idx) +
                       ": parent_idx out of range or not an ancestor");
    // a var is visible if declared in this block or an ancestor
    auto visible = [&](const std::string& name) {
      const BlockDesc* cur = &b;
      int hops = 0;
      while (cur && hops++ <= nblocks) {     // bounded even on bad input
        if (cur->vars.count(name)) return true;
        // parent must be a real, earlier block — idx is self-declared and
        // may lie, so bound by nblocks too (OOB read otherwise)
        cur = (cur->parent_idx >= 0 && cur->parent_idx < nblocks &&
               cur->parent_idx < cur->idx)
                  ? &prog.blocks[cur->parent_idx]
                  : nullptr;
      }
      return false;
    };
    for (size_t oi = 0; oi < b.ops.size(); ++oi) {
      const OpDesc& op = b.ops[oi];
      std::string where = "block " + std::to_string(b.idx) + " op#" +
                          std::to_string(oi) + " (" + op.type + ")";
      if (op.type.empty()) errors.push_back(where + ": empty op type");
      for (auto& n : op.all_inputs())
        if (!n.empty() && !visible(n))
          errors.push_back(where + ": input var '" + n + "' not declared");
      for (auto& n : op.all_outputs())
        if (!n.empty() && !visible(n))
          errors.push_back(where + ": output var '" + n + "' not declared");
      for (int bi : op.block_attrs())
        if (bi < 0 || bi >= nblocks)
          errors.push_back(where + ": sub-block index " + std::to_string(bi) +
                           " out of range");
    }
  }
  return errors;
}

// ---------------------------------------------------------------------------
// scheduling + liveness + reuse planning
// ---------------------------------------------------------------------------

struct BlockAnalysis {
  std::vector<int> topo_order;          // op indices in dependency order
  std::vector<int> level;               // parallel wavefront per op
  std::vector<int> last_use;            // per op: ops whose outputs die here
  std::map<std::string, std::pair<int, int>> live_range;  // var -> [def,last]
  std::map<std::string, int> reuse_slot;  // var -> buffer slot id
  int num_slots = 0;
};

// Kahn topo sort over def-use edges, preserving program order among ready
// ops (stable) — mirrors how the reference executor's sequential order is a
// valid schedule, while exposing wavefronts the reference never computed.
BlockAnalysis analyze_block(const ProgramDesc& prog, int block_idx) {
  const BlockDesc& b = prog.blocks.at(block_idx);
  int n = (int)b.ops.size();
  BlockAnalysis out;
  std::unordered_map<std::string, int> last_writer;
  std::vector<std::vector<int>> succ(n);
  std::vector<int> indeg(n, 0);
  for (int i = 0; i < n; ++i) {
    std::set<int> preds;
    for (auto& name : b.ops[i].all_inputs()) {
      auto it = last_writer.find(name);
      if (it != last_writer.end()) preds.insert(it->second);
    }
    // write-after-write: order multiple writers of the same var
    for (auto& name : b.ops[i].all_outputs()) {
      auto it = last_writer.find(name);
      if (it != last_writer.end()) preds.insert(it->second);
    }
    for (int p : preds) {
      succ[p].push_back(i);
      indeg[i]++;
    }
    for (auto& name : b.ops[i].all_outputs()) last_writer[name] = i;
  }
  std::priority_queue<int, std::vector<int>, std::greater<int>> ready;
  for (int i = 0; i < n; ++i)
    if (!indeg[i]) ready.push(i);
  std::vector<int> level(n, 0);
  while (!ready.empty()) {
    int i = ready.top();
    ready.pop();
    out.topo_order.push_back(i);
    for (int s : succ[i]) {
      level[s] = std::max(level[s], level[i] + 1);
      if (--indeg[s] == 0) ready.push(s);
    }
  }
  out.level = level;

  // liveness over the (stable) topo order
  std::unordered_map<std::string, int> def_pos, last_pos;
  for (int pos = 0; pos < (int)out.topo_order.size(); ++pos) {
    int i = out.topo_order[pos];
    for (auto& name : b.ops[i].all_outputs())
      if (!def_pos.count(name)) def_pos[name] = pos;
    for (auto& name : b.ops[i].all_inputs()) last_pos[name] = pos;
    for (auto& name : b.ops[i].all_outputs()) last_pos[name] = pos;
  }
  for (auto& kv : def_pos) {
    const std::string& name = kv.first;
    auto vit = b.vars.find(name);
    bool pers = vit != b.vars.end() && vit->second.persistable;
    if (pers) continue;  // parameters never recycle
    out.live_range[name] = {kv.second, last_pos[name]};
  }

  // greedy interval-graph coloring = the reference transpiler's var-reuse
  // (memory_optimization_transpiler.py:259 memory_optimize), done natively.
  std::vector<std::pair<std::pair<int, int>, std::string>> ivs;
  for (auto& kv : out.live_range)
    ivs.push_back({kv.second, kv.first});
  std::sort(ivs.begin(), ivs.end());
  // slot -> position where it frees
  std::vector<int> free_at;
  for (auto& iv : ivs) {
    int start = iv.first.first, end = iv.first.second;
    int slot = -1;
    for (int s = 0; s < (int)free_at.size(); ++s)
      if (free_at[s] < start) {
        slot = s;
        break;
      }
    if (slot < 0) {
      slot = (int)free_at.size();
      free_at.push_back(-1);
    }
    free_at[slot] = end;
    out.reuse_slot[iv.second] = slot;
  }
  out.num_slots = (int)free_at.size();
  return out;
}

// ---------------------------------------------------------------------------
// inference pruning — the native engine behind fluid.io.prune_program
// (reference framework Program.prune / prune.cc): backward slice of the
// global block to the ops needed for `targets`; returns kept op indices.
// ---------------------------------------------------------------------------

std::vector<int> prune_block(const ProgramDesc& prog, int block_idx,
                             const std::vector<std::string>& targets) {
  const BlockDesc& b = prog.blocks.at(block_idx);
  std::unordered_set<std::string> needed(targets.begin(), targets.end());
  std::vector<int> keep;
  for (int i = (int)b.ops.size() - 1; i >= 0; --i) {
    bool hit = false;
    for (auto& n : b.ops[i].all_outputs())
      if (needed.count(n)) { hit = true; break; }
    if (!hit) continue;
    keep.push_back(i);
    for (auto& n : b.ops[i].all_inputs())
      if (!n.empty()) needed.insert(n);
  }
  std::reverse(keep.begin(), keep.end());
  return keep;
}

std::string analysis_to_json(const BlockAnalysis& a) {
  auto root = Json::make(Json::OBJECT);
  auto topo = Json::make(Json::ARRAY);
  for (int i : a.topo_order) topo->arr.push_back(Json::of_int(i));
  root->obj["topo_order"] = topo;
  auto lev = Json::make(Json::ARRAY);
  for (int l : a.level) lev->arr.push_back(Json::of_int(l));
  root->obj["level"] = lev;
  auto lr = Json::make(Json::OBJECT);
  for (auto& kv : a.live_range) {
    auto pr = Json::make(Json::ARRAY);
    pr->arr.push_back(Json::of_int(kv.second.first));
    pr->arr.push_back(Json::of_int(kv.second.second));
    lr->obj[kv.first] = pr;
  }
  root->obj["live_range"] = lr;
  auto rs = Json::make(Json::OBJECT);
  for (auto& kv : a.reuse_slot) rs->obj[kv.first] = Json::of_int(kv.second);
  root->obj["reuse_slot"] = rs;
  root->obj["num_slots"] = Json::of_int(a.num_slots);
  std::string out;
  write_json(root, &out);
  return out;
}

}  // namespace ptpu

// ---------------------------------------------------------------------------
// C ABI — the ctypes surface (paddle_tpu/native/__init__.py loads this .so).
// Every entry returns a malloc'd NUL-terminated string the caller frees with
// ptpu_free; errors come back as {"error": "..."} JSON.
// ---------------------------------------------------------------------------

#include <cstring>

namespace {

char* dup_out(const std::string& s) {
  char* p = (char*)std::malloc(s.size() + 1);
  std::memcpy(p, s.c_str(), s.size() + 1);
  return p;
}

char* error_out(const std::string& msg) {
  auto root = ptpu::Json::make(ptpu::Json::OBJECT);
  root->obj["error"] = ptpu::Json::of_str(msg);
  std::string out;
  ptpu::write_json(root, &out);
  return dup_out(out);
}

}  // namespace

extern "C" {

void ptpu_free(char* p) { std::free(p); }

// canonical re-serialization (fingerprint parity with desc.py)
char* ptpu_reserialize(const char* text) {
  try {
    return dup_out(ptpu::reserialize(text));
  } catch (const std::exception& e) {
    return error_out(e.what());
  }
}

// -> JSON array of error strings (empty array = valid)
char* ptpu_validate(const char* text) {
  try {
    auto prog = ptpu::parse_program(text);
    auto errs = ptpu::validate_program(prog);
    auto root = ptpu::Json::make(ptpu::Json::ARRAY);
    for (auto& m : errs) root->arr.push_back(ptpu::Json::of_str(m));
    std::string out;
    ptpu::write_json(root, &out);
    return dup_out(out);
  } catch (const std::exception& e) {
    return error_out(e.what());
  }
}

// -> {"topo_order":[...], "level":[...], "live_range":{...},
//     "reuse_slot":{...}, "num_slots":N}
char* ptpu_analyze(const char* text, int block_idx) {
  try {
    auto prog = ptpu::parse_program(text);
    auto a = ptpu::analyze_block(prog, block_idx);
    return dup_out(ptpu::analysis_to_json(a));
  } catch (const std::exception& e) {
    return error_out(e.what());
  }
}

// targets_json: JSON array of var names -> JSON array of kept op indices
char* ptpu_prune(const char* text, int block_idx, const char* targets_json) {
  try {
    auto prog = ptpu::parse_program(text);
    ptpu::JsonParser tp(targets_json);
    auto tj = tp.parse();
    std::vector<std::string> targets;
    for (auto& e : tj->arr) targets.push_back(e->s);
    auto keep = ptpu::prune_block(prog, block_idx, targets);
    auto root = ptpu::Json::make(ptpu::Json::ARRAY);
    for (int i : keep) root->arr.push_back(ptpu::Json::of_int(i));
    std::string out;
    ptpu::write_json(root, &out);
    return dup_out(out);
  } catch (const std::exception& e) {
    return error_out(e.what());
  }
}

}  // extern "C"
