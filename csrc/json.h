// Minimal JSON value model + parser + canonical writer.
//
// The program IR's wire format is canonical JSON (sorted keys, no spaces) —
// see paddle_tpu/fluid/core/desc.py serialize_to_string.  This parser/writer
// round-trips that format byte-identically, which is how the C++ core and
// the Python front end prove they agree on the graph (fingerprint equality).
// Counterpart of the reference's protobuf layer (paddle/framework/
// framework.proto + program_desc.cc).
#pragma once

#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace ptpu {

class Json;
using JsonPtr = std::shared_ptr<Json>;

class Json {
 public:
  enum Type { NUL, BOOL, INT, DOUBLE, STRING, ARRAY, OBJECT };

  Type type = NUL;
  bool b = false;
  int64_t i = 0;
  double d = 0.0;
  std::string s;
  std::vector<JsonPtr> arr;
  std::map<std::string, JsonPtr> obj;  // std::map => sorted keys for free

  static JsonPtr make(Type t) {
    auto j = std::make_shared<Json>();
    j->type = t;
    return j;
  }
  static JsonPtr of_int(int64_t v) {
    auto j = make(INT);
    j->i = v;
    return j;
  }
  static JsonPtr of_str(const std::string& v) {
    auto j = make(STRING);
    j->s = v;
    return j;
  }
  static JsonPtr of_bool(bool v) {
    auto j = make(BOOL);
    j->b = v;
    return j;
  }

  bool is_null() const { return type == NUL; }
  const JsonPtr& at(const std::string& k) const {
    auto it = obj.find(k);
    if (it == obj.end()) throw std::runtime_error("missing key: " + k);
    return it->second;
  }
  JsonPtr get(const std::string& k) const {
    auto it = obj.find(k);
    return it == obj.end() ? nullptr : it->second;
  }
};

// ---------------------------------------------------------------------------
// parser
// ---------------------------------------------------------------------------

class JsonParser {
 public:
  explicit JsonParser(const std::string& text) : t_(text) {}

  JsonPtr parse() {
    JsonPtr v = value();
    ws();
    if (p_ != t_.size()) fail("trailing characters");
    return v;
  }

 private:
  const std::string& t_;
  size_t p_ = 0;

  [[noreturn]] void fail(const std::string& msg) {
    throw std::runtime_error("json parse error at " + std::to_string(p_) +
                             ": " + msg);
  }
  void ws() {
    while (p_ < t_.size() && (t_[p_] == ' ' || t_[p_] == '\t' ||
                              t_[p_] == '\n' || t_[p_] == '\r'))
      ++p_;
  }
  char peek() {
    if (p_ >= t_.size()) fail("unexpected end");
    return t_[p_];
  }
  void expect(char c) {
    if (peek() != c) fail(std::string("expected '") + c + "'");
    ++p_;
  }
  bool consume(const char* lit) {
    size_t n = strlen(lit);
    if (t_.compare(p_, n, lit) == 0) {
      p_ += n;
      return true;
    }
    return false;
  }

  JsonPtr value() {
    ws();
    char c = peek();
    switch (c) {
      case '{': return object();
      case '[': return array();
      case '"': return Json::of_str(string());
      case 't':
        if (consume("true")) return Json::of_bool(true);
        fail("bad literal");
      case 'f':
        if (consume("false")) return Json::of_bool(false);
        fail("bad literal");
      case 'n':
        if (consume("null")) return Json::make(Json::NUL);
        fail("bad literal");
      default: return number();
    }
  }

  JsonPtr object() {
    expect('{');
    auto j = Json::make(Json::OBJECT);
    ws();
    if (peek() == '}') {
      ++p_;
      return j;
    }
    while (true) {
      ws();
      std::string k = string();
      ws();
      expect(':');
      j->obj[k] = value();
      ws();
      if (peek() == ',') {
        ++p_;
        continue;
      }
      expect('}');
      return j;
    }
  }

  JsonPtr array() {
    expect('[');
    auto j = Json::make(Json::ARRAY);
    ws();
    if (peek() == ']') {
      ++p_;
      return j;
    }
    while (true) {
      j->arr.push_back(value());
      ws();
      if (peek() == ',') {
        ++p_;
        continue;
      }
      expect(']');
      return j;
    }
  }

  std::string string() {
    expect('"');
    std::string out;
    while (true) {
      if (p_ >= t_.size()) fail("unterminated string");
      char c = t_[p_++];
      if (c == '"') return out;
      if (c == '\\') {
        if (p_ >= t_.size()) fail("bad escape");
        char e = t_[p_++];
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            if (p_ + 4 > t_.size()) fail("bad \\u escape");
            unsigned cp = std::stoul(t_.substr(p_, 4), nullptr, 16);
            p_ += 4;
            // encode UTF-8 (surrogate pairs for completeness)
            if (cp >= 0xD800 && cp <= 0xDBFF) {
              if (p_ + 6 > t_.size() || t_[p_] != '\\' || t_[p_ + 1] != 'u')
                fail("unpaired surrogate");
              unsigned lo = std::stoul(t_.substr(p_ + 2, 4), nullptr, 16);
              p_ += 6;
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            }
            if (cp < 0x80) {
              out += static_cast<char>(cp);
            } else if (cp < 0x800) {
              out += static_cast<char>(0xC0 | (cp >> 6));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            } else if (cp < 0x10000) {
              out += static_cast<char>(0xE0 | (cp >> 12));
              out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            } else {
              out += static_cast<char>(0xF0 | (cp >> 18));
              out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
              out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            }
            break;
          }
          default: fail("bad escape");
        }
      } else {
        out += c;
      }
    }
  }

  JsonPtr number() {
    size_t start = p_;
    if (peek() == '-') ++p_;
    while (p_ < t_.size() && isdigit(t_[p_])) ++p_;
    bool is_double = false;
    if (p_ < t_.size() && t_[p_] == '.') {
      is_double = true;
      ++p_;
      while (p_ < t_.size() && isdigit(t_[p_])) ++p_;
    }
    if (p_ < t_.size() && (t_[p_] == 'e' || t_[p_] == 'E')) {
      is_double = true;
      ++p_;
      if (p_ < t_.size() && (t_[p_] == '+' || t_[p_] == '-')) ++p_;
      while (p_ < t_.size() && isdigit(t_[p_])) ++p_;
    }
    std::string tok = t_.substr(start, p_ - start);
    if (tok.empty() || tok == "-") fail("bad number");
    auto j = std::make_shared<Json>();
    if (is_double) {
      j->type = Json::DOUBLE;
      j->d = std::stod(tok);
    } else {
      j->type = Json::INT;
      j->i = std::stoll(tok);
    }
    return j;
  }
};

// ---------------------------------------------------------------------------
// canonical writer — must byte-match python json.dumps(sort_keys=True,
// separators=(",", ":")) for the values the IR produces
// ---------------------------------------------------------------------------

inline void write_json(const JsonPtr& j, std::string* out);

inline void write_escaped(const std::string& s, std::string* out) {
  // byte-matches python json.dumps default ensure_ascii=True: control
  // chars and ALL non-ascii code points escape to \uXXXX (surrogate
  // pairs above the BMP); UTF-8 is decoded here for that purpose
  out->push_back('"');
  size_t i = 0, n = s.size();
  while (i < n) {
    unsigned char c = (unsigned char)s[i];
    if (c < 0x80) {
      switch (c) {
        case '"': *out += "\\\""; break;
        case '\\': *out += "\\\\"; break;
        case '\b': *out += "\\b"; break;
        case '\f': *out += "\\f"; break;
        case '\n': *out += "\\n"; break;
        case '\r': *out += "\\r"; break;
        case '\t': *out += "\\t"; break;
        default:
          if (c < 0x20 || c == 0x7F) {   // python escapes DEL too
            char buf[8];
            snprintf(buf, sizeof buf, "\\u%04x", c);
            *out += buf;
          } else {
            out->push_back((char)c);
          }
      }
      ++i;
      continue;
    }
    // decode one UTF-8 sequence -> code point
    uint32_t cp = 0xFFFD;
    size_t len = 1;
    if ((c & 0xE0) == 0xC0 && i + 1 < n) {
      cp = ((c & 0x1Fu) << 6) | ((unsigned char)s[i + 1] & 0x3Fu);
      len = 2;
    } else if ((c & 0xF0) == 0xE0 && i + 2 < n) {
      cp = ((c & 0x0Fu) << 12) | (((unsigned char)s[i + 1] & 0x3Fu) << 6) |
           ((unsigned char)s[i + 2] & 0x3Fu);
      len = 3;
    } else if ((c & 0xF8) == 0xF0 && i + 3 < n) {
      cp = ((c & 0x07u) << 18) | (((unsigned char)s[i + 1] & 0x3Fu) << 12) |
           (((unsigned char)s[i + 2] & 0x3Fu) << 6) |
           ((unsigned char)s[i + 3] & 0x3Fu);
      len = 4;
    }
    char buf[16];
    if (cp <= 0xFFFF) {
      snprintf(buf, sizeof buf, "\\u%04x", cp);
      *out += buf;
    } else {
      uint32_t v = cp - 0x10000;
      snprintf(buf, sizeof buf, "\\u%04x\\u%04x",
               0xD800 + (v >> 10), 0xDC00 + (v & 0x3FF));
      *out += buf;
    }
    i += len;
  }
  out->push_back('"');
}

// python repr(float) — shortest round-trip representation
inline std::string double_repr(double d) {
  for (int prec = 1; prec <= 17; ++prec) {
    std::ostringstream os;
    os.precision(prec);
    os << d;
    if (std::stod(os.str()) == d) {
      std::string s = os.str();
      // python always renders a decimal point or exponent for floats
      if (s.find('.') == std::string::npos &&
          s.find('e') == std::string::npos &&
          s.find("inf") == std::string::npos &&
          s.find("nan") == std::string::npos)
        s += ".0";
      return s;
    }
  }
  return "0.0";
}

inline void write_json(const JsonPtr& j, std::string* out) {
  if (!j) {
    *out += "null";
    return;
  }
  switch (j->type) {
    case Json::NUL: *out += "null"; break;
    case Json::BOOL: *out += j->b ? "true" : "false"; break;
    case Json::INT: *out += std::to_string(j->i); break;
    case Json::DOUBLE: *out += double_repr(j->d); break;
    case Json::STRING: write_escaped(j->s, out); break;
    case Json::ARRAY: {
      out->push_back('[');
      for (size_t k = 0; k < j->arr.size(); ++k) {
        if (k) out->push_back(',');
        write_json(j->arr[k], out);
      }
      out->push_back(']');
      break;
    }
    case Json::OBJECT: {
      out->push_back('{');
      bool first = true;
      for (auto& kv : j->obj) {
        if (!first) out->push_back(',');
        first = false;
        write_escaped(kv.first, out);
        out->push_back(':');
        write_json(kv.second, out);
      }
      out->push_back('}');
      break;
    }
  }
}

}  // namespace ptpu
